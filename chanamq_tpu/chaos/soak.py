"""The chaos soak: a 3-node replicated workload under a seeded fault plan.

Shared by ``bench.py --chaos`` and ``tests/test_chaos.py`` so the tier-1
smoke and the test suite assert the same invariants:

1. **No confirmed message lost** — every body whose publisher confirm
   arrived is delivered to the consumer at least once.
2. **No double-delivery after settle** — duplicates during failover are
   at-least-once reality and merely counted; once the workload settles
   (everything delivered, surviving owner's queue empty, observation
   window passed) no further delivery may arrive.
3. **Exactly one failover promotion** — the owner crash promotes exactly
   one replica, cluster-wide.
4. **Cursors resume at committed offsets** — a stream consumer that
   detaches and reattaches at "next" resumes at committed+1 and reads
   contiguously to the tail.
5. **Reconnect stays inside the backoff budget** — the publisher finishes
   every message despite injected disconnects/partitions, and no stream's
   backoff delay ever exceeds the configured ceiling.
6. **Health gates and alerts are deterministic** — both nodes must report
   ready (telemetry/health.py) before any load is offered, and a scripted
   backlog + stalled-consumer phase on the surviving node must fire
   exactly the expected alert rules: the telemetry services are
   tick-driven by the harness (no timers), so the alert engine sees the
   same series every run and the firing set is exact, like the fault
   schedule itself.

Topology: three nodes A, B, C with private stores (MemoryStore by
default; ``wal=True`` gives every node a WAL-fronted SQLite store so the
group-fsync confirm gate sits in the durability path under chaos),
replicate factor 2, sync confirms. Queue ``rq`` is owned by A with its
replica placed on B, but published AND consumed via B, so every message
crosses the data plane twice (push B->A, deliver A->B) and every confirm
gates on A's mutation-log ship back to B. Mid-run a crash rule kills A;
B must promote its replica and finish the workload locally while C looks
on — exactly one promotion cluster-wide (the replica holder), but BOTH
survivors observe the DOWN and re-hash the ring once each. The stream
queue lives on B (replica on C) and survives the crash.

Determinism: the publisher consults the plan once per message at the
``soak.tick`` site, so the crash fires at a fixed publish index for a
given seed. Transport-site rules use invocation windows, making their
schedule a pure function of the seed as well (see plan.py).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from . import ChaosStore, FaultPlan, FaultRule, _LazyRuntime, clear, install

# logical crash-target name the plan uses; the harness maps it to node A
CRASH_TARGET = "owner"

BACKOFF_BUDGET_S = 5.0  # ReconnectBackoff max_s: no delay may exceed it


def default_plan(seed: int, owner: str, messages: int) -> FaultPlan:
    """The full seeded soak: partitions + node crash + slow store +
    transport latency/disconnects. Windows are invocation-indexed so the
    schedule is deterministic per seed; the crash rides the publisher's
    ``soak.tick`` so it lands at a fixed publish index. Transport faults
    that can strand state on A (lost settles, dropped deliver batches)
    are windowed BEFORE the crash: failover requeues them from B's
    replica, which is exactly the recovery the soak must prove."""
    crash_at = max(10, int(messages * 0.55))
    return FaultPlan(seed, [
        FaultRule(name="crash-owner", kind="crash", sites=["soak.tick"],
                  after=crash_at, count=1, nodes=[CRASH_TARGET]),
        FaultRule(name="partition-to-owner", kind="partition",
                  sites=["data.send"], nodes=[owner], after=20, until=45),
        FaultRule(name="drop-deliver", kind="drop", sites=["data.event"],
                  count=2, after=5, until=crash_at),
        FaultRule(name="disconnect-data", kind="disconnect",
                  sites=["data.read"], probability=0.05, count=2,
                  until=crash_at),
        FaultRule(name="wire-latency", kind="latency",
                  sites=["data.send", "rpc.call"], probability=0.05,
                  delay_ms=3),
        FaultRule(name="slow-store", kind="latency", sites=["store.flush"],
                  probability=0.3, delay_ms=8),
    ])


async def run_soak(
    seed: int, *, messages: int = 160, stream_records: int = 40,
    plan: Optional[FaultPlan] = None, metrics_sink=None,
    uds: bool = False, wal: bool = False,
) -> dict:
    """Run the workload under the plan; returns a report whose
    ``violations`` list is empty iff every invariant held.

    ``uds=True`` runs the interconnect over Unix-domain sockets — the
    exact transport sibling shards use (shard/) — so the crash becomes
    the shard-crash drill: same plan, same invariants, plus ownership
    re-hashes observed by each survivor.

    ``wal=True`` backs every node with a WAL-fronted SQLite store
    (wal/engine.py over a private temp dir): confirms then gate on the
    cross-channel group fsync, and the slow-store rule stalls the WAL
    commit barrier itself — proving the no-confirmed-loss invariant with
    the real durability engine in the path, not a memory stand-in."""
    import os
    import shutil
    import tempfile

    from ..amqp.properties import BasicProperties
    from ..client.client import AMQPClient
    from ..store.memory import MemoryStore
    from ..broker.server import BrokerServer
    from ..cluster.node import ClusterNode
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults

    uds_dir = tempfile.mkdtemp(prefix="chanamq-soak-") if uds else None
    wal_dir = tempfile.mkdtemp(prefix="chanamq-soak-wal-") if wal else None
    wal_count = 0

    def make_store():
        if not wal:
            return MemoryStore()
        nonlocal wal_count
        from ..store.sqlite import SqliteStore
        from ..wal import WalStore
        wal_count += 1
        path = os.path.join(wal_dir, f"node{wal_count}.db")
        return WalStore(SqliteStore(path), flush_ms=1.0, checkpoint_ms=200.0)

    async def start_node(seeds, uds_path=None):
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=make_store())
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                         heartbeat_interval_s=0.2, failure_timeout_s=1.5,
                         replicate_factor=2, replicate_sync=True,
                         replicate_ack_timeout_ms=2000,
                         uds_path=uds_path)
        await cl.start()
        # tick-driven telemetry: the harness calls sample_tick at scripted
        # points instead of starting the timer task, so the alert engine's
        # input series — and therefore its firings — are exact. Node-scoped
        # rules get unreachable thresholds (loop lag and replication lag
        # depend on host timing, which would make firings flaky).
        srv.broker.telemetry = TelemetryService(
            srv.broker, interval_s=1.0, ring_ticks=64,
            rules=alert_defaults(
                backlog_growth=50.0, backlog_window=5, stall_ticks=3,
                repl_lag=1e12, loop_lag_ms=1e12))
        return srv, cl

    a_srv = a_cl = b_srv = b_cl = c_srv = c_cl = None
    conns: list = []
    violations: list[str] = []
    try:
        a_path = os.path.join(uds_dir, "a.sock") if uds_dir else None
        b_path = os.path.join(uds_dir, "b.sock") if uds_dir else None
        c_path = os.path.join(uds_dir, "c.sock") if uds_dir else None
        a_srv, a_cl = await start_node([], uds_path=a_path)
        b_srv, b_cl = await start_node([a_cl.name], uds_path=b_path)
        c_srv, c_cl = await start_node([a_cl.name], uds_path=c_path)
        if uds:
            # ephemeral cluster ports: names exist only after start, so
            # the sibling map is patched in afterwards (real shards use
            # fixed base+index ports and get the map at construction)
            for cl, path in ((a_cl, a_path), (b_cl, b_path), (c_cl, c_path)):
                for other, opath in ((a_cl, a_path), (b_cl, b_path),
                                     (c_cl, c_path)):
                    if other is not cl:
                        cl.uds_map[other.name] = opath
        clusters = (a_cl, b_cl, c_cl)
        for _ in range(100):
            if all(len(cl.membership.alive_members()) == 3
                   for cl in clusters):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("3-node membership did not converge")

        # -- health gate (invariant 6a): all nodes ready before any load
        health_gate: dict[str, bool] = {}
        for srv, cl in ((a_srv, a_cl), (b_srv, b_cl), (c_srv, c_cl)):
            srv.broker.telemetry.sample_tick(1.0)
            health = srv.broker.telemetry.health()
            health_gate[cl.name] = health["ready"]
            if not health["ready"]:
                violations.append(
                    f"health gate: {cl.name} not ready before load: "
                    f"{health['reasons']}")

        # placement is pinned, not just ownership: rq's replica must sit
        # on B (the consumer's node) so the crash promotes where the
        # consumer already is, and sq's on C so the stream's sync-confirm
        # path never gates on the dead node
        def placed(prefix, owner, replica):
            return next(
                f"{prefix}{i}" for i in range(2000)
                if a_cl.ring.preference_entity("q", "/", f"{prefix}{i}", 2)
                == [owner.name, replica.name])

        rq = placed("cq", a_cl, b_cl)
        sq = placed("cs", b_cl, c_cl)

        if plan is None:
            plan = default_plan(seed, a_cl.name, messages)
        runtime = install(plan, metrics=metrics_sink or b_srv.broker.metrics)
        fingerprint = plan.fingerprint()
        # store seams on both nodes (the slow-store rule hits the flush
        # barrier); the lazy shim keeps them live across install/clear
        a_srv.broker.store = ChaosStore(a_srv.broker.store, _LazyRuntime())
        b_srv.broker.store = ChaosStore(b_srv.broker.store, _LazyRuntime())
        c_srv.broker.store = ChaosStore(c_srv.broker.store, _LazyRuntime())

        crashed = asyncio.Event()

        def crash_owner() -> None:
            async def _die():
                # abrupt stop: no drain ordering — B must detect the
                # silence (no leave protocol) and promote
                for part in (a_cl, a_srv):
                    try:
                        await part.stop()
                    except Exception:
                        pass
                crashed.set()
            asyncio.get_event_loop().create_task(_die())

        runtime.on_crash(CRASH_TARGET, crash_owner)

        # -- consumer on B (remote consumer of A's queue, then local
        #    consumer of the promoted replica after the crash)
        persistent = BasicProperties(delivery_mode=2)
        deliveries: dict[str, int] = {}
        settle_mark = asyncio.Event()
        post_settle: list[str] = []
        delivered_event = asyncio.Event()

        c_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        conns.append(c_conn)
        c_ch = await c_conn.channel()
        await c_ch.basic_qos(prefetch_count=64)

        def on_msg(msg):
            body = bytes(msg.body).decode()
            deliveries[body] = deliveries.get(body, 0) + 1
            if settle_mark.is_set():
                post_settle.append(body)
            c_ch.basic_ack(msg.delivery_tag)
            delivered_event.set()

        p_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        conns.append(p_conn)
        p_ch = await p_conn.channel()
        await p_ch.confirm_select()
        await p_ch.queue_declare(rq, durable=True)
        for _ in range(100):
            if ("/", rq) in b_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        await c_ch.basic_consume(rq, on_msg, consumer_tag="soak-consumer")

        # -- publisher: one confirm-gated message at a time, reconnecting
        #    through aborts/partitions; soak.tick drives the crash index
        confirmed: set[int] = set()
        attempts = 0
        max_backoff_seen = 0.0

        def observe_backoff() -> None:
            nonlocal max_backoff_seen
            for cl in (b_cl,):
                for plane in cl._dataplanes.values():
                    for st in plane.stats()["backoff"]:
                        max_backoff_seen = max(max_backoff_seen,
                                               st["delay_s"])

        async def reconnect_publisher():
            nonlocal p_conn, p_ch
            try:
                await p_conn.close()
            except Exception:
                pass
            p_conn = await AMQPClient.connect("127.0.0.1",
                                              b_srv.bound_port)
            conns.append(p_conn)
            p_ch = await p_conn.channel()
            await p_ch.confirm_select()

        for i in range(messages):
            runtime.decide("soak.tick")  # deterministic crash index
            body = f"m{i:06d}".encode()
            for attempt in range(60):
                attempts += 1
                try:
                    await p_ch.basic_publish_confirmed(
                        body, routing_key=rq, properties=persistent,
                        timeout=8)
                    confirmed.add(i)
                    break
                except Exception:
                    observe_backoff()
                    await asyncio.sleep(0.25)
                    try:
                        await reconnect_publisher()
                    except Exception:
                        pass  # next attempt retries the dial
            else:
                violations.append(
                    f"publish m{i:06d} never confirmed within the "
                    f"reconnect budget")
                break
        observe_backoff()

        # -- drain: every confirmed body delivered at least once, then the
        #    surviving owner's queue runs empty (requeued strays included)
        want = {f"m{i:06d}" for i in confirmed}

        def surviving_queue():
            for srv in (b_srv, c_srv, a_srv):
                if srv is None:
                    continue
                vhost = srv.broker.vhosts.get("/")
                queue = vhost.queues.get(rq) if vhost else None
                if queue is not None and queue.consumer_count:
                    return queue
            return None

        deadline = asyncio.get_event_loop().time() + 45
        while asyncio.get_event_loop().time() < deadline:
            queue = surviving_queue()
            if (want <= set(deliveries) and queue is not None
                    and queue.message_count == 0
                    and not queue.outstanding):
                break
            delivered_event.clear()
            try:
                await asyncio.wait_for(delivered_event.wait(), 0.25)
            except asyncio.TimeoutError:
                pass
        missing = sorted(want - set(deliveries))
        if missing:
            violations.append(
                f"confirmed-but-lost: {len(missing)} messages "
                f"(first: {missing[:5]})")

        # -- settle: duplicates beyond this point violate invariant 2
        settle_mark.set()
        await asyncio.sleep(0.7)
        duplicates = sum(n - 1 for n in deliveries.values() if n > 1)
        if post_settle:
            violations.append(
                f"{len(post_settle)} deliveries after settle "
                f"(first: {post_settle[:5]})")

        # -- promotion accounting (A's metrics survive its stop)
        promotions = (a_srv.broker.metrics.repl_promotions
                      + b_srv.broker.metrics.repl_promotions
                      + c_srv.broker.metrics.repl_promotions)
        # ownership re-hash accounting: each DOWN event a node observes
        # re-hashes the ring once and bumps shard_handoffs; with 3 nodes
        # BOTH survivors observe the crash (one re-hash each), but only
        # the replica holder (B) promotes — so a crash run must show
        # exactly two re-hashes and exactly one promotion cluster-wide,
        # and a clean run none of either
        handoffs = (a_srv.broker.metrics.shard_handoffs
                    + b_srv.broker.metrics.shard_handoffs
                    + c_srv.broker.metrics.shard_handoffs)
        expect_crash = any(r.kind == "crash" for r in plan.rules)
        if expect_crash:
            if not crashed.is_set():
                violations.append("crash rule never fired")
            if promotions != 1:
                violations.append(
                    f"expected exactly 1 promotion, saw {promotions}")
            if handoffs != 2:
                violations.append(
                    f"expected exactly 2 ownership re-hashes "
                    f"(one per survivor), saw {handoffs}")
        else:
            if promotions:
                violations.append(f"unexpected promotion(s): {promotions}")
            if handoffs:
                violations.append(
                    f"unexpected ownership re-hash(es): {handoffs}")

        if max_backoff_seen > BACKOFF_BUDGET_S:
            violations.append(
                f"backoff delay {max_backoff_seen:.2f}s exceeded the "
                f"{BACKOFF_BUDGET_S}s budget")

        # -- stream cursor resume (on B, which survived)
        stream = await _stream_cursor_check(
            b_srv, sq, stream_records, violations)

        # -- deterministic alert firings (invariant 6b) on the survivor
        alerts = await _alert_phase(b_srv, b_cl, violations)

        return {
            "seed": seed,
            "fingerprint": fingerprint,
            "nodes": 3,
            "store": "wal+sqlite" if wal else "memory",
            "replicate_factor": 2,
            "messages": messages,
            "confirmed": len(confirmed),
            "publish_attempts": attempts,
            "delivered_unique": len(set(deliveries) & want),
            "duplicates": duplicates,
            "post_settle_duplicates": len(post_settle),
            "promotions": promotions,
            "handoffs": handoffs,
            "interconnect": "uds" if uds else "tcp",
            "crashed": crashed.is_set(),
            "max_backoff_s": round(max_backoff_seen, 3),
            "stream": stream,
            "health_gate": health_gate,
            "alerts": alerts,
            "chaos": runtime.status(),
            "violations": violations,
        }
    finally:
        clear()
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        for part in (c_cl, c_srv, b_cl, b_srv, a_cl, a_srv):
            if part is not None:
                try:
                    await part.stop()
                except Exception:
                    pass
        if wal_dir is not None:
            shutil.rmtree(wal_dir, ignore_errors=True)


# the scripted alert phase must fire exactly these rules, every run
EXPECTED_ALERT_RULES = ("backlog-growth", "consumer-stall")


async def _alert_phase(srv, cl, violations: list[str]) -> dict:
    """Invariant 6b: drive the surviving node's telemetry through a
    scripted backlog (publish with no consumer -> backlog-growth) and a
    stalled consumer (prefetch 1, never acks -> consumer-stall), ticking
    the sampler by hand. The engine's input is then a pure function of
    the workload, so the set of fired rules must match
    EXPECTED_ALERT_RULES exactly — no more, no fewer."""
    from ..client.client import AMQPClient

    svc = srv.broker.telemetry
    aq = next(f"ca{i}" for i in range(200)
              if cl.queue_owner("/", f"ca{i}") == cl.name)
    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    try:
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare(aq)
        # baseline tick: the queue's ring slot needs one pre-backlog
        # sample for the growth window to measure against
        svc.sample_tick(1.0)
        for i in range(120):
            ch.basic_publish(f"a{i:04d}".encode(), routing_key=aq)
        await ch.wait_unconfirmed_below(1, timeout=15)
        # two post-backlog ticks: +120 depth inside the 5-tick window on
        # both -> breach streak reaches for_ticks=2 -> backlog-growth fires
        svc.sample_tick(1.0)
        svc.sample_tick(1.0)

        # stalled consumer: prefetch 1, never acks. The first delivery
        # lands before the next tick (deliver_rate blips once), then the
        # queue has depth > 0, consumers > 0 and zero deliver rate for
        # stall_ticks=3 straight ticks -> consumer-stall fires
        first = asyncio.Event()
        await ch.basic_qos(prefetch_count=1)
        await ch.basic_consume(aq, lambda msg: first.set(),
                               consumer_tag="stalled")
        await asyncio.wait_for(first.wait(), 10)
        for _ in range(4):
            svc.sample_tick(1.0)

        snapshot = svc.engine.snapshot()
        fired = tuple(snapshot["fired_rules"])
        if fired != EXPECTED_ALERT_RULES:
            violations.append(
                f"alert firings not exact: expected {EXPECTED_ALERT_RULES}, "
                f"got {fired}")
        return {
            "queue": aq,
            "fired_rules": list(fired),
            "fired_total": snapshot["fired_total"],
            "resolved_total": snapshot["resolved_total"],
            "firing_now": [
                f"{i['rule']}:{i['entity']}" for i in snapshot["firing"]],
        }
    finally:
        try:
            await conn.close()
        except Exception:
            pass


async def _stream_cursor_check(
    srv, sq: str, records: int, violations: list[str]
) -> dict:
    """Invariant 4: publish a stream, ack half under one tag, detach,
    reattach at "next" — deliveries must resume at committed+1 and run
    contiguously to the tail."""
    from ..amqp.properties import BasicProperties
    from ..client.client import AMQPClient

    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    try:
        pch = await conn.channel()
        await pch.confirm_select()
        await pch.queue_declare(
            sq, durable=True, arguments={"x-queue-type": "stream"})
        props = BasicProperties(delivery_mode=2)
        for i in range(records):
            pch.basic_publish(f"s{i:06d}".encode(), routing_key=sq,
                              properties=props)
        await pch.wait_unconfirmed_below(1, timeout=30)

        half = records // 2
        first_leg: list = []
        got_half = asyncio.Event()
        ch1 = await conn.channel()
        await ch1.basic_qos(prefetch_count=records + 8)

        def leg1(msg):
            first_leg.append((msg.delivery_tag, bytes(msg.body).decode()))
            if len(first_leg) == half:
                got_half.set()

        await ch1.basic_consume(
            sq, leg1, consumer_tag="soak-cursor",
            arguments={"x-stream-offset": "first"})
        await asyncio.wait_for(got_half.wait(), 15)
        # commit the cursor through record half-1, then detach
        ch1.basic_ack(first_leg[half - 1][0], multiple=True)
        await asyncio.sleep(0.3)  # let the commit land
        await ch1.basic_cancel("soak-cursor")

        second_leg: list = []
        done = asyncio.Event()
        ch2 = await conn.channel()
        await ch2.basic_qos(prefetch_count=records + 8)

        def leg2(msg):
            second_leg.append(bytes(msg.body).decode())
            if len(second_leg) >= records - half:
                done.set()

        await ch2.basic_consume(
            sq, leg2, consumer_tag="soak-cursor",
            arguments={"x-stream-offset": "next"})
        try:
            await asyncio.wait_for(done.wait(), 15)
        except asyncio.TimeoutError:
            pass
        expected = [f"s{i:06d}" for i in range(half, records)]
        resumed_ok = second_leg[:len(expected)] == expected \
            and len(second_leg) >= len(expected)
        if not resumed_ok:
            violations.append(
                f"stream cursor did not resume contiguously at committed+1 "
                f"(expected s{half:06d}.., got {second_leg[:3]})")
        return {
            "records": records,
            "committed_through": half - 1,
            "resumed_at": second_leg[0] if second_leg else None,
            "contiguous": resumed_ok,
        }
    finally:
        try:
            await conn.close()
        except Exception:
            pass
