"""Queue replication with failover promotion.

Turns the cluster from "sharded" into "sharded + HA": each replicated
queue's owner ships its store mutations (enqueue, settle, purge, delete,
watermark moves) as a sequenced, batched event log to factor-1 follower
nodes, which maintain a warm passive copy in their local store under a
replica namespace. When the owner dies, the highest-synced follower
promotes: it materializes its copy into the real namespace, claims the
queue cluster-wide, and the existing consumer-reconcile path re-attaches
consumers. With chana.mq.replicate.sync=true, publisher confirms gate on
follower acks so no confirmed persistent message can be lost to a single
node failure.
"""

from .applier import ReplicaApplier, ReplicaCopy
from .log import QueueRepLog, ReplicationManager

__all__ = [
    "QueueRepLog",
    "ReplicationManager",
    "ReplicaApplier",
    "ReplicaCopy",
]
