"""Live broker telemetry: the feature vectors the forecaster trains on.

This is the wiring between chanamq_tpu.utils.metrics (counters + gauges,
maintained on the broker's hot paths) and chanamq_tpu.models.forecaster
(the JAX model): each sampler tick turns the counter deltas and queue
gauges into one 8-feature vector and appends it to a fixed-size ring
buffer. The ring is plain numpy — no JAX import, no device work — so the
sampler can run on the broker's event loop at negligible cost; training
and prediction read *copies* of the ring from a worker thread
(models/service.py) and never touch broker state.

The reference has no analogue (it had no metrics subsystem at all,
SURVEY.md §5 "observability"); SURVEY.md §7.1 scopes JAX to exactly this
role: batch analytics over broker metrics, never on the message path.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

# One vector per sampler tick. Rates are per-second deltas of the metrics
# counters; depth/unacked/consumers are instantaneous gauges summed over
# every queue in every vhost (matching models/forecaster.py:3-7).
FEATURES: tuple[str, ...] = (
    "publish_rate",        # messages published / s
    "deliver_rate",        # messages delivered / s
    "depth",               # ready messages across all queues
    "unacked",             # outstanding (unacked) deliveries
    "consumers",           # registered consumers
    "publish_bytes_rate",  # body bytes published / s
    "deliver_bytes_rate",  # body bytes delivered / s
    "confirm_rate",        # publisher confirms / s
)

N_FEATURES = len(FEATURES)

# counter names backing the rate features, in feature order
_RATE_COUNTERS = (
    "published_msgs", "delivered_msgs", "published_bytes",
    "delivered_bytes", "confirmed_msgs",
)
_RATE_INDEX = (0, 1, 5, 6, 7)  # position of each rate in FEATURES


def counter_state(broker: "Broker") -> dict[str, int]:
    """Snapshot the monotonic counters a rate delta needs."""
    metrics = broker.metrics
    return {name: getattr(metrics, name) for name in _RATE_COUNTERS}


def sample(
    broker: "Broker", prev: dict[str, int], dt_s: float
) -> tuple[np.ndarray, dict[str, int]]:
    """One telemetry vector from the broker's live metrics.

    prev is the counter snapshot from the previous tick; dt_s the elapsed
    wall time since then. Returns (vector[N_FEATURES] float32, new snapshot).
    """
    current = counter_state(broker)
    vec = np.zeros(N_FEATURES, dtype=np.float32)
    dt = max(dt_s, 1e-6)
    for (name, idx) in zip(_RATE_COUNTERS, _RATE_INDEX):
        vec[idx] = (current[name] - prev.get(name, 0)) / dt
    # O(1): the broker maintains these gauges incrementally at every queue
    # mutation site (entities.py), so a tick costs the same at 10 queues
    # as at 10k — the old per-tick walk over every queue in every vhost
    # was O(all queues) and would dominate the loop at scale
    vec[2] = broker.queue_depth
    vec[3] = broker.queue_unacked
    vec[4] = broker.queue_consumers
    return vec, current


class TelemetryRing:
    """Fixed-capacity ring of telemetry vectors (newest-last windows).

    Single-writer (the sampler task on the event loop); readers take
    consistent copies via window()/history() and may run on any thread.
    """

    def __init__(self, capacity: int = 4096, width: int = N_FEATURES) -> None:
        assert capacity > 1
        self.capacity = capacity
        self.width = width
        self._buf = np.zeros((capacity, width), dtype=np.float32)
        self._next = 0   # write position
        self.count = 0   # total vectors ever pushed

    def push(self, vec: np.ndarray) -> None:
        self._buf[self._next] = vec
        self._next = (self._next + 1) % self.capacity
        self.count += 1

    def __len__(self) -> int:
        return min(self.count, self.capacity)

    def history(self) -> np.ndarray:
        """All retained vectors, oldest first (copy)."""
        n = len(self)
        if self.count <= self.capacity:
            return self._buf[:n].copy()
        # ring has wrapped: stitch [next:] + [:next] (concatenate already
        # allocates a fresh array)
        return np.concatenate([self._buf[self._next:], self._buf[:self._next]])

    def window(self, seq_len: int) -> Optional[np.ndarray]:
        """The newest seq_len vectors, oldest first; None if not enough."""
        if len(self) < seq_len:
            return None
        return self.history()[-seq_len:]

    def latest(self) -> Optional[np.ndarray]:
        if len(self) == 0:
            return None
        return self._buf[(self._next - 1) % self.capacity].copy()


class TopKSlots:
    """Identity-pinned feature slots for the per-queue forecaster columns.

    The old tap (TelemetryService.topk_features) re-ranked queues every
    tick and wrote "the i-th busiest queue" into slot i. Whenever the
    top-K *set* changed between ticks, a feature column silently changed
    meaning mid-window — the model saw queue A's depth spliced onto
    queue B's history and trained on the seam. Here a slot, once
    assigned, stays bound to the same queue for as long as that queue
    remains in the top-K set; membership changes are explicit:

    - eviction: a queue that drops out of the current top-K frees its
      slot (the slot emits zeros from that tick on),
    - reset: a newly assigned slot emits zeros for exactly one tick (the
      reset marker), so the window shows a clean break instead of a
      discontinuous splice between two queues' series.

    Assignment of new entrants to freed slots follows rank order, so the
    mapping is deterministic for a given telemetry series.
    """

    def __init__(self, k: int) -> None:
        self.k = max(0, int(k))
        self._keys: list[Optional[tuple]] = [None] * self.k

    def slot_queues(self) -> list[Optional[tuple]]:
        """Current slot -> queue identity binding (None = free)."""
        return list(self._keys)

    def update(self, keys: list, latest: np.ndarray) -> np.ndarray:
        """One tick: re-rank, evict/assign, and emit the 2k feature tail
        (depth, publish_rate per slot) aligned to the pinned bindings.

        keys/latest are EntityRings.latest_matrix() output (QUEUE_FIELDS
        column order: publish_rate, deliver_rate, ack_rate, depth, ...).
        """
        out = np.zeros(2 * self.k, dtype=np.float32)
        if self.k == 0:
            return out
        desired: list[tuple] = []
        if keys:
            rate = latest[:, 0] + latest[:, 1]
            order = np.argsort(-rate, kind="stable")[: self.k]
            desired = [tuple(keys[i]) for i in order]
        desired_set = set(desired)
        # evict slots whose queue left the top-K set
        freed: list[int] = []
        for slot, key in enumerate(self._keys):
            if key is not None and key not in desired_set:
                self._keys[slot] = None
            if self._keys[slot] is None:
                freed.append(slot)
        # assign new entrants to freed slots in rank order; fresh slots
        # emit zeros this tick (the reset marker)
        occupied = {key for key in self._keys if key is not None}
        entrants = [key for key in desired if key not in occupied]
        fresh: set[int] = set()
        for slot, key in zip(freed, entrants):
            self._keys[slot] = key
            fresh.add(slot)
        index = {tuple(key): i for i, key in enumerate(keys)}
        for slot, key in enumerate(self._keys):
            if key is None or slot in fresh:
                continue
            row = index.get(key)
            if row is None:
                continue  # vanished this tick; evicted on the next update
            out[2 * slot] = latest[row, 3]      # depth
            out[2 * slot + 1] = latest[row, 0]  # publish_rate
        return out


def training_batch(
    history: np.ndarray, seq_len: int, batch: int, rng: np.random.Generator
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Sample `batch` (window, next-vector) training pairs from a history
    array (as returned by TelemetryRing.history()). Returns (x, y) with
    x [batch, seq_len, N_FEATURES] and y [batch, N_FEATURES], or None if
    the history is too short for even one pair."""
    n = len(history)
    if n < seq_len + 1:
        return None
    starts = rng.integers(0, n - seq_len, size=batch)
    x = np.stack([history[s:s + seq_len] for s in starts])
    y = np.stack([history[s + seq_len] for s in starts])
    return x.astype(np.float32), y.astype(np.float32)


def normalization(history: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-feature (mean, std) over a history array; std floored so a
    constant feature (e.g. consumers under steady load) never divides by
    zero."""
    mean = history.mean(axis=0)
    std = np.maximum(history.std(axis=0), 1e-3)
    return mean.astype(np.float32), std.astype(np.float32)
