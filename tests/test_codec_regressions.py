"""Regression tests for codec defects found in review/verification."""

import decimal

import pytest

from chanamq_tpu.amqp import value_codec as vc
from chanamq_tpu.amqp import methods as m
from chanamq_tpu.amqp.command import AMQCommand
from chanamq_tpu.amqp.frame import FrameError, FrameParser
from chanamq_tpu.amqp.properties import BasicProperties


def test_decimal_positive_exponent_roundtrip():
    # 1E+2 must survive as 100, not be scaled down to 1
    out = vc.decode_table(vc.encode_table({"d": decimal.Decimal("1E+2")}))
    assert out["d"] == 100


def test_non_utf8_longstr_reencodes_verbatim():
    raw = b"\x00\x00\x00\x09\x01kS\x00\x00\x00\x02\xff\xfe"
    assert vc.encode_table(vc.decode_table(raw)) == raw


def test_methods_with_tables_are_hashable():
    assert isinstance(hash(m.Queue.Declare(arguments={"x": 1})), int)
    assert hash(m.Basic.Ack(delivery_tag=1)) != hash(m.Basic.Ack(delivery_tag=2))


def test_render_rejects_degenerate_frame_max():
    cmd = AMQCommand(1, m.Basic.Publish(exchange="e"), BasicProperties(), b"abc")
    for bad in (1, 7, 8):
        with pytest.raises(ValueError):
            cmd.render_frames(bad)


def test_parser_rejects_garbage_from_header_alone():
    # corrupt stream with a huge bogus size field must error immediately,
    # not buffer gigabytes waiting for it
    out = list(FrameParser().feed(b"\x41" * 12))
    assert isinstance(out[0], FrameError)
