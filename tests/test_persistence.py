"""Durability and recovery tests: SQLite store + broker restart.

The HA contract of the reference (README.md:47-49, recovery call stack
SURVEY.md §3.6): durable + persistent state survives broker death and is
recovered from the store on the next start.
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.store.api import StoredExchange, StoredMessage, StoredQueue
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


@pytest.fixture
def db_path(tmp_path):
    return str(tmp_path / "broker.db")


async def start_server(db_path):
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    return srv


# ---------------------------------------------------------------------------
# store unit tests
# ---------------------------------------------------------------------------


async def test_sqlite_message_roundtrip(db_path):
    store = SqliteStore(db_path)
    await store.open()
    msg = StoredMessage(id=7, properties_raw=b"\x01\x02", body=b"body",
                        exchange="ex", routing_key="rk", refer_count=2,
                        ttl_ms=5000)
    await store.insert_message(msg)
    got = await store.select_message(7)
    assert got == msg
    await store.update_message_refer_count(7, 1)
    assert (await store.select_message(7)).refer_count == 1
    await store.delete_message(7)
    assert await store.select_message(7) is None
    await store.close()


async def test_sqlite_queue_roundtrip(db_path):
    store = SqliteStore(db_path)
    await store.open()
    q = StoredQueue(vhost="/", name="q1", durable=True, ttl_ms=1000,
                    arguments={"x-message-ttl": 1000})
    await store.insert_queue_meta(q)
    await store.insert_queue_msg("/", "q1", 1, 100, 10, None)
    await store.insert_queue_msg("/", "q1", 2, 101, 20, 9999999999999)
    await store.insert_queue_unacks("/", "q1", [(99, 0, 5, None)])
    got = await store.select_queue("/", "q1")
    assert got.name == "q1"
    assert got.ttl_ms == 1000
    assert got.msgs == [(1, 100, 10, None), (2, 101, 20, 9999999999999)]
    assert got.unacks == {99: (0, 5, None)}
    # watermark advance prunes the log
    await store.update_queue_last_consumed("/", "q1", 1)
    got = await store.select_queue("/", "q1")
    assert got.last_consumed == 1
    assert got.msgs == [(2, 101, 20, 9999999999999)]
    await store.delete_queue_unacks("/", "q1", [99])
    assert (await store.select_queue("/", "q1")).unacks == {}
    await store.close()


async def test_sqlite_exchange_binds_roundtrip(db_path):
    store = SqliteStore(db_path)
    await store.open()
    await store.insert_exchange(StoredExchange(
        vhost="/", name="ex", type="topic", durable=True))
    await store.insert_bind("/", "ex", "q1", "a.*", None)
    await store.insert_bind("/", "ex", "q2", "a.#", {"x": 1})
    got = await store.select_exchange("/", "ex")
    assert got.type == "topic"
    assert sorted(got.binds) == [("a.#", "q2", {"x": 1}), ("a.*", "q1", None)]
    await store.delete_bind("/", "ex", "q1", "a.*")
    assert len((await store.select_exchange("/", "ex")).binds) == 1
    await store.delete_queue_binds("/", "q2")
    assert (await store.select_exchange("/", "ex")).binds == []
    await store.close()


async def test_sqlite_archive_on_delete(db_path):
    store = SqliteStore(db_path)
    await store.open()
    await store.insert_queue_meta(StoredQueue(vhost="/", name="dq", durable=True))
    await store.insert_queue_msg("/", "dq", 1, 500, 9, None)
    await store.archive_queue("/", "dq")
    await store.delete_queue("/", "dq")
    assert await store.select_queue("/", "dq") is None
    # archival copies exist (reference: *_deleted tables)
    def q(db):
        rows = db.execute("SELECT * FROM queue_msgs_deleted").fetchall()
        metas = db.execute("SELECT * FROM queue_metas_deleted").fetchall()
        return rows, metas
    rows, metas = await store._submit(q)
    assert len(rows) == 1 and rows[0][3] == 500
    assert len(metas) == 1
    await store.close()


# ---------------------------------------------------------------------------
# broker restart recovery
# ---------------------------------------------------------------------------


async def test_durable_entities_survive_restart(db_path):
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.exchange_declare("dur_ex", "topic", durable=True)
    await ch.queue_declare("dur_q", durable=True)
    await ch.queue_bind("dur_q", "dur_ex", "logs.#")
    for i in range(5):
        ch.basic_publish(f"p{i}".encode(), exchange="dur_ex",
                         routing_key="logs.app", properties=PERSISTENT)
    await asyncio.sleep(0.1)
    await c.close()
    await srv.stop()

    # new broker process-equivalent: fresh server over the same file
    srv2 = await start_server(db_path)
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("dur_q", passive=True)
        assert ok.message_count == 5
        # the binding also survived: publish routes again
        ch2.basic_publish(b"p5", exchange="dur_ex", routing_key="logs.db",
                          properties=PERSISTENT)
        await asyncio.sleep(0.1)
        bodies = []
        for _ in range(6):
            m = await ch2.basic_get("dur_q", no_ack=True)
            bodies.append(m.body)
        assert bodies == [b"p0", b"p1", b"p2", b"p3", b"p4", b"p5"]
        await c2.close()
    finally:
        await srv2.stop()


async def test_transient_messages_do_not_survive_restart(db_path):
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("mix_q", durable=True)
    ch.basic_publish(b"persistent", routing_key="mix_q", properties=PERSISTENT)
    ch.basic_publish(b"transient", routing_key="mix_q")  # delivery_mode unset
    await asyncio.sleep(0.1)
    await c.close()
    await srv.stop()

    srv2 = await start_server(db_path)
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("mix_q", passive=True)
        assert ok.message_count == 1
        m = await ch2.basic_get("mix_q", no_ack=True)
        assert m.body == b"persistent"
        await c2.close()
    finally:
        await srv2.stop()


async def test_unacked_messages_recovered_after_crash(db_path):
    """Deliver without ack, kill the broker: the message must come back
    (redeliverable) after restart — the reference's unack table reload."""
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("crash_q", durable=True)
    got = []
    await ch.basic_consume("crash_q", lambda m: got.append(m))  # no ack sent
    ch.basic_publish(b"inflight", routing_key="crash_q", properties=PERSISTENT)
    await asyncio.sleep(0.2)
    assert len(got) == 1
    # crash: no clean client close, no ack
    await srv.stop()

    srv2 = await start_server(db_path)
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("crash_q", passive=True)
        assert ok.message_count == 1
        m = await ch2.basic_get("crash_q", no_ack=True)
        assert m.body == b"inflight"
        await c2.close()
    finally:
        await srv2.stop()


async def test_unacked_survive_double_crash(db_path):
    """Review regression: recovery converts unack rows back into queue-log
    rows, so a second crash before redelivery still retains the message."""
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("dd_q", durable=True)
    got = []
    await ch.basic_consume("dd_q", lambda m: got.append(m))
    ch.basic_publish(b"sticky", routing_key="dd_q", properties=PERSISTENT)
    await asyncio.sleep(0.2)
    await srv.stop()  # crash 1 with message unacked

    srv2 = await start_server(db_path)
    await srv2.stop()  # crash 2 before anyone consumed

    srv3 = await start_server(db_path)
    try:
        c3 = await AMQPClient.connect("127.0.0.1", srv3.bound_port)
        ch3 = await c3.channel()
        m = await ch3.basic_get("dd_q", no_ack=True)
        assert m is not None and m.body == b"sticky"
        await c3.close()
    finally:
        await srv3.stop()


async def test_acked_messages_not_recovered(db_path):
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("done_q", durable=True)
    ch.basic_publish(b"done", routing_key="done_q", properties=PERSISTENT)
    await asyncio.sleep(0.1)
    m = await ch.basic_get("done_q")
    ch.basic_ack(m.delivery_tag)
    await asyncio.sleep(0.1)
    await c.close()
    await srv.stop()

    srv2 = await start_server(db_path)
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("done_q", passive=True)
        assert ok.message_count == 0
        await c2.close()
    finally:
        await srv2.stop()


async def test_deleted_queue_not_recovered(db_path):
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("gone_q", durable=True)
    ch.basic_publish(b"x", routing_key="gone_q", properties=PERSISTENT)
    await asyncio.sleep(0.1)
    await ch.queue_delete("gone_q")
    await c.close()
    await srv.stop()

    srv2 = await start_server(db_path)
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        from chanamq_tpu.client.client import ChannelClosedError

        with pytest.raises(ChannelClosedError):
            await ch2.queue_declare("gone_q", passive=True)
        await c2.close()
    finally:
        await srv2.stop()


async def test_vhosts_survive_restart(db_path):
    srv = await start_server(db_path)
    await srv.broker.create_vhost("tenant-a")
    await srv.stop()
    srv2 = await start_server(db_path)
    try:
        c = await AMQPClient.connect("127.0.0.1", srv2.bound_port, vhost="tenant-a")
        ch = await c.channel()
        ok = await ch.queue_declare("t_q")
        assert ok.queue == "t_q"
        await c.close()
    finally:
        await srv2.stop()


async def test_message_refcount_deleted_when_all_queues_ack(db_path):
    """A message fanned to 2 durable queues is deleted from the store only
    after both copies are consumed (reference: MessageEntity refcount)."""
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.exchange_declare("fan2", "fanout", durable=True)
    await ch.queue_declare("f_q1", durable=True)
    await ch.queue_declare("f_q2", durable=True)
    await ch.queue_bind("f_q1", "fan2", "")
    await ch.queue_bind("f_q2", "fan2", "")
    ch.basic_publish(b"shared", exchange="fan2", properties=PERSISTENT)
    await asyncio.sleep(0.1)
    store = srv.broker.store

    m1 = await ch.basic_get("f_q1", no_ack=True)
    assert m1.body == b"shared"
    await asyncio.sleep(0.1)
    msgs = await store._submit(lambda db: db.execute("SELECT id FROM msgs").fetchall())
    assert len(msgs) == 1  # still referenced by f_q2

    m2 = await ch.basic_get("f_q2", no_ack=True)
    await asyncio.sleep(0.1)
    msgs = await store._submit(lambda db: db.execute("SELECT id FROM msgs").fetchall())
    assert msgs == []  # refcount hit zero -> blob deleted

    await c.close()
    await srv.stop()


async def test_flush_barrier_surfaces_covered_write_failure(db_path):
    """flush() is the confirm durability barrier: a fire-and-forget write
    that fails inside the batch must fail the barrier, not just a log line
    (otherwise a publisher confirm could paper over a lost persistent
    message)."""
    store = SqliteStore(db_path)
    await store.open()
    # fire-and-forget failing op (single statement against a missing table)
    bad = store._submit(
        lambda db: db.execute("INSERT INTO no_such_table VALUES (1)"),
        guard=False)
    bad.add_done_callback(lambda f: f.exception())  # consume, like store_bg
    with pytest.raises(Exception):
        await store.flush()
    # the store keeps working afterwards; a clean barrier passes
    await store.insert_message(StoredMessage(
        id=1, properties_raw=b"", body=b"x", exchange="", routing_key="q",
        refer_count=1))
    await store.flush()
    assert (await store.select_message(1)) is not None
    await store.close()


async def test_flush_idle_fast_path_surfaces_earlier_failure(db_path):
    """ADVICE r2: a fire-and-forget write that fails in a batch completing
    BEFORE flush() is called must still fail the next barrier — the idle
    fast path must not return an already-done success future over an
    unreported failure."""
    store = SqliteStore(db_path)
    await store.open()
    bad = store._submit(
        lambda db: db.execute("INSERT INTO no_such_table VALUES (1)"),
        guard=False)
    bad.add_done_callback(lambda f: f.exception())  # consume, like store_bg
    # let the failing batch fully complete so flush() takes the fast path
    for _ in range(50):
        await asyncio.sleep(0.01)
        if not store._pending and not store._batch_in_flight:
            break
    assert not store._pending and not store._batch_in_flight
    with pytest.raises(Exception):
        await store.flush()
    # reported once; the store keeps working and a clean barrier passes
    await store.flush()
    await store.close()


async def test_flush_attribution_two_confirm_publishers(db_path):
    """VERDICT r3 #6: with two confirm-mode connections, a store failure on
    B's insert must fail ONLY B's durability barrier — A gets a clean
    confirm, and A's barrier must not consume the failure report out from
    under B's (the round-3 consume-once scar)."""
    srv = await start_server(db_path)
    store = srv.broker.store
    orig_insert = store.insert_message_nowait

    def failing_insert(msg):
        if msg.routing_key == "qb":
            store._submit_nowait(
                lambda db: db.execute("INSERT INTO no_such_table VALUES (1)"))
            return
        orig_insert(msg)

    store.insert_message_nowait = failing_insert
    a = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    b = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cha = await a.channel()
    chb = await b.channel()
    await cha.confirm_select()
    await chb.confirm_select()
    await cha.queue_declare("qa", durable=True)
    await chb.queue_declare("qb", durable=True)

    # both publishes race into the same group-commit window
    chb.basic_publish(b"lost", routing_key="qb", properties=PERSISTENT)
    cha.basic_publish(b"kept", routing_key="qa", properties=PERSISTENT)

    # A's barrier covers only A's writes: clean confirm
    await cha.wait_unconfirmed_below(1, timeout=10)
    # B must never see a confirm for the lost message: its barrier raises
    # and the server drops the connection
    with pytest.raises(Exception):
        await chb.wait_unconfirmed_below(1, timeout=10)
    assert len(chb.unconfirmed) == 1  # the publish was never confirmed

    # A's message really is durable
    store.insert_message_nowait = orig_insert
    await a.close()
    await b.close()
    await srv.stop()
    srv2 = await start_server(db_path)
    c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
    ch2 = await c2.channel()
    got = await ch2.basic_get("qa", no_ack=True)
    assert got is not None and got.body == b"kept"
    await c2.close()
    await srv2.stop()


async def test_group_commit_batches_many_writes(db_path):
    """Writes enqueued in one tick commit together and all resolve."""
    store = SqliteStore(db_path)
    await store.open()
    futs = [store.insert_message(StoredMessage(
        id=i, properties_raw=b"", body=b"b", exchange="", routing_key="q",
        refer_count=1)) for i in range(500)]
    await asyncio.gather(*futs)
    for i in (0, 250, 499):
        assert (await store.select_message(i)) is not None
    await store.close()


# ---------------------------------------------------------------------------
# store API contract: metas strip bodies; MemoryStore writes are eager
# ---------------------------------------------------------------------------


async def test_select_message_metas_strips_bodies_for_any_backend(db_path):
    """select_message_metas must never return bodies: recovery counts on
    rebuilding deep backlogs without blob bytes in RAM, for every backend
    (the SQLite override also skips the blob read; the base default strips
    after the fact so third-party stores keep the contract)."""
    from chanamq_tpu.store.memory import MemoryStore

    for store in (MemoryStore(), SqliteStore(db_path)):
        await store.open()
        await store.insert_message(StoredMessage(
            id=11, properties_raw=b"\x01", body=b"blob-bytes",
            exchange="ex", routing_key="rk", refer_count=1))
        metas = await store.select_message_metas([11])
        assert metas[11].body is None, type(store).__name__
        assert metas[11].refer_count == 1
        # and the stored row is untouched (stripping hit a copy)
        full = await store.select_message(11)
        assert full.body == b"blob-bytes", type(store).__name__
        await store.close()


async def test_memory_store_writes_apply_at_call_time():
    """MemoryStore writes take effect at call time (program order == store
    order, like SqliteStore._submit): a read issued with ZERO event-loop
    yields after a fire-and-forget write must see it — the broker's paged
    transient bodies depend on this (store_bg(insert) then an inline
    basic_get read)."""
    from chanamq_tpu.store.memory import MemoryStore

    store = MemoryStore()
    await store.open()
    aw = store.insert_message(StoredMessage(
        id=5, properties_raw=b"", body=b"x", exchange="e",
        routing_key="r", refer_count=1))
    # no await of the write yet — read anyway
    got = await store.select_message(5)
    assert got is not None and got.body == b"x"
    await aw  # completed awaitable is still awaitable
    del_aw = store.delete_message(5)
    assert await store.select_message(5) is None
    await del_aw


async def test_store_synchronous_knob(tmp_path):
    """chana.mq.store.synchronous plumbs through config to the PRAGMA:
    FULL fsyncs every group commit (power-loss durability), NORMAL is the
    WAL default (process-crash durability). Bad values fail fast."""
    from chanamq_tpu.config import Config
    from chanamq_tpu.broker.server import BrokerServer

    cfg = Config({
        "chana.mq.store.path": str(tmp_path / "full.db"),
        "chana.mq.store.synchronous": "FULL",
        "chana.mq.amqp.port": 0,
    })
    srv = BrokerServer.from_config(cfg)
    await srv.start()
    assert srv.broker.store.synchronous == "FULL"
    # PRAGMA actually applied on the open connection (2 == FULL)
    level = await srv.broker.store._submit(
        lambda db: db.execute("PRAGMA synchronous").fetchone()[0])
    assert level == 2, level
    await srv.stop()

    with pytest.raises(ValueError):
        SqliteStore(str(tmp_path / "bad.db"), synchronous="SOMETIMES")


async def test_sigkill_crash_loop_loses_no_confirmed_message(tmp_path):
    """Single-node durability under repeated hard crashes: a confirm-mode
    publisher records every CONFIRMED persistent message; SIGKILL the broker
    process mid-flow three times; after the final recovery, every confirmed
    message is present exactly once, in order (confirms may lag — unconfirmed
    messages may or may not survive, but confirmed ones MUST)."""
    import signal
    import socket
    import subprocess
    import sys

    db = str(tmp_path / "crash.db")
    port_holder = {}

    async def start_broker():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = subprocess.Popen(
            [sys.executable, "-m", "chanamq_tpu.broker.server",
             "--host", "127.0.0.1", "--port", str(port), "--store", db,
             "--no-admin", "--log-level", "WARNING"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for _ in range(150):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"broker died at startup (rc={proc.returncode})")
            try:
                _, w = await asyncio.open_connection("127.0.0.1", port)
                w.close()
                break
            except OSError:
                await asyncio.sleep(0.05)
        else:
            proc.kill()
            raise RuntimeError("broker never came up")
        port_holder["port"] = port
        return proc

    confirmed: list[int] = []
    seq = 0

    async def publish_some(n):
        """Publish n persistent messages; record exactly the seqs whose
        confirm arrived (tags are 1-based per fresh channel, and this
        broker never Basic.Nacks — a failed barrier hard-closes instead —
        so a tag absent from ch.unconfirmed IS a durable confirm)."""
        nonlocal seq
        c = await AMQPClient.connect("127.0.0.1", port_holder["port"])
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare("crash_q", durable=True)
        tag_to_seq = {}
        for _ in range(n):
            tag = ch.basic_publish(seq.to_bytes(8, "big"),
                                   routing_key="crash_q",
                                   properties=PERSISTENT)
            tag_to_seq[tag] = seq
            seq += 1
        try:
            await ch.wait_unconfirmed_below(1, timeout=10)
        except Exception:
            pass  # crash raced the confirms; count what actually arrived
        pending = set(ch.unconfirmed)
        confirmed.extend(s for t, s in tag_to_seq.items() if t not in pending)
        try:
            await c.close()
        except Exception:
            pass

    proc = await start_broker()
    try:
        for round_no in range(3):
            await publish_some(400)
            # crash mid-life: some publishes of the NEXT burst race the kill
            burst = asyncio.create_task(publish_some(200))
            await asyncio.sleep(0.05)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            try:
                await asyncio.wait_for(burst, timeout=10)
            except asyncio.TimeoutError:
                burst.cancel()
            except (OSError, ConnectionError):
                pass  # connect lost the race with the kill: nothing published
            proc = await start_broker()
        # final recovery: drain and check every confirmed id is present
        # exactly once, in order
        c = await AMQPClient.connect("127.0.0.1", port_holder["port"])
        ch = await c.channel()
        got = []
        while True:
            m = await ch.basic_get("crash_q", no_ack=True)
            if m is None:
                break
            got.append(int.from_bytes(m.body, "big"))
        confirmed_set = set(confirmed)
        present = [g for g in got if g in confirmed_set]
        assert len(got) == len(set(got)), "duplicate delivery after recovery"
        assert confirmed_set.issubset(set(got)), (
            f"lost {sorted(confirmed_set - set(got))[:10]} confirmed messages")
        assert present == sorted(present), "confirmed messages out of order"
        await c.close()
    finally:
        try:
            proc.kill()
            proc.wait(timeout=5)
        except Exception:
            pass
