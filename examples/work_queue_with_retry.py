#!/usr/bin/env python3
"""Retry-topology demo using the broker's RabbitMQ-style extensions the
reference never implemented: a capped work queue dead-letters failures into
a TTL'd retry queue whose own DLX routes them back, jobs are submitted in a
tx batch, and the consumer inspects x-death to give up after 3 attempts.

Usage: python examples/work_queue_with_retry.py [host] [port]
(defaults to a broker on 127.0.0.1:5672 — start one with
`python -m chanamq_tpu.broker.server` or `chanamq-server`)
"""

import asyncio
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from chanamq_tpu.client import AMQPClient

RETRY_DELAY_MS = 500
MAX_ATTEMPTS = 3


async def main() -> None:
    host = sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1"
    port = int(sys.argv[2]) if len(sys.argv) > 2 else 5672
    c = await AMQPClient.connect(host, port)
    ch = await c.channel()

    # work -> (reject) -> retry_ex -> retry queue -TTL-> work_ex -> work
    await ch.exchange_declare("work_ex", "direct", durable=True)
    await ch.exchange_declare("retry_ex", "direct", durable=True)
    await ch.queue_declare("work", durable=True, arguments={
        "x-dead-letter-exchange": "retry_ex",
        "x-max-length": 10_000,
    })
    await ch.queue_bind("work", "work_ex", "job")
    await ch.queue_declare("work.retry", durable=True, arguments={
        "x-message-ttl": RETRY_DELAY_MS,
        "x-dead-letter-exchange": "work_ex",
    })
    await ch.queue_bind("work.retry", "retry_ex", "job")

    # submit a batch of jobs atomically: all-or-nothing via tx.commit
    await ch.tx_select()
    for i in range(5):
        ch.basic_publish(b"job-%d" % i, exchange="work_ex",
                         routing_key="job")
    await ch.tx_commit()
    print("submitted 5 jobs in one committed tx batch")

    done = asyncio.get_event_loop().create_future()
    seen: dict[bytes, int] = {}

    def on_job(msg):
        deaths = (msg.properties.headers or {}).get("x-death") or []
        attempts = next((d["count"] for d in deaths
                         if d.get("queue") == "work"
                         and d.get("reason") == "rejected"), 0)
        seen[msg.body] = attempts + 1
        if msg.body == b"job-3" and attempts < MAX_ATTEMPTS - 1:
            # simulate a failing job: reject -> retry queue -> redelivery
            print(f"{msg.body.decode()}: attempt {attempts + 1} failed, "
                  f"retrying in {RETRY_DELAY_MS}ms")
            consume_ch.basic_reject(msg.delivery_tag, requeue=False)
        else:
            verb = "gave up on" if attempts else "processed"
            print(f"{verb} {msg.body.decode()} "
                  f"(attempt {attempts + 1})")
            consume_ch.basic_ack(msg.delivery_tag)
        if len(seen) == 5 and seen.get(b"job-3", 0) >= MAX_ATTEMPTS:
            if not done.done():
                done.set_result(None)

    consume_ch = await c.channel()
    await consume_ch.basic_qos(prefetch_count=16)
    await consume_ch.basic_consume("work", on_job)
    await asyncio.wait_for(done, timeout=30)
    await c.close()


if __name__ == "__main__":
    asyncio.run(main())
