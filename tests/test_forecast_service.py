"""Forecast service integration: live broker telemetry -> ring -> off-path
JAX train/predict -> GET /admin/forecast + Prometheus gauges.

This is the wiring test VERDICT r4 asked for: the broker runs under real
client load, the sampler sees *observed* traffic (not synthetic_batch —
that helper is for unit tests only), and the admin endpoint serves a finite
next-tick forecast derived from it."""

import asyncio
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chanamq_tpu.broker.server import BrokerServer  # noqa: E402
from chanamq_tpu.client import AMQPClient  # noqa: E402
from chanamq_tpu.models.service import ForecastService  # noqa: E402
from chanamq_tpu.models.telemetry import (  # noqa: E402
    FEATURES, N_FEATURES, TelemetryRing, training_batch,
)
from chanamq_tpu.rest.admin import AdminServer  # noqa: E402

pytestmark = pytest.mark.asyncio


@pytest.fixture(scope="module", autouse=True)
def force_cpu():
    jax.config.update("jax_platforms", "cpu")


# -- ring unit tests ---------------------------------------------------------


def test_ring_window_and_wrap():
    ring = TelemetryRing(capacity=10)
    assert ring.window(4) is None
    for i in range(25):
        vec = np.full(N_FEATURES, float(i), dtype=np.float32)
        ring.push(vec)
    assert len(ring) == 10
    assert ring.count == 25
    history = ring.history()
    # oldest-first across the wrap point
    assert [int(v[0]) for v in history] == list(range(15, 25))
    window = ring.window(4)
    assert [int(v[0]) for v in window] == [21, 22, 23, 24]
    assert int(ring.latest()[0]) == 24


def test_training_batch_pairs_align():
    rng = np.random.default_rng(0)
    history = np.arange(20, dtype=np.float32)[:, None].repeat(N_FEATURES, 1)
    pairs = training_batch(history, seq_len=5, batch=8, rng=rng)
    assert pairs is not None
    x, y = pairs
    assert x.shape == (8, 5, N_FEATURES)
    assert y.shape == (8, N_FEATURES)
    # y is the vector immediately after each window
    for i in range(8):
        assert y[i][0] == x[i][-1][0] + 1
    assert training_batch(history[:5], 5, 8, rng) is None


# -- end-to-end: broker under load -> forecast over the admin API ------------


async def _http_get(port: int, path: str) -> tuple[str, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return head.decode("latin-1").split("\r\n")[0], body


async def test_forecast_from_observed_traffic():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    forecaster = ForecastService(
        server.broker,
        interval_s=0.02,
        train_interval_s=0.2,
        seq_len=8,
        # ring must retain the load-era samples across the first round's
        # jit compile (ticks keep coming while it runs): 4096 * 0.02s = 80s
        history=4096,
        batch=8,
        steps_per_round=5,
        model_kwargs={"d_model": 32, "n_heads": 4, "d_ff": 64, "n_layers": 1},
    )
    await forecaster.start()
    client = await AMQPClient.connect("127.0.0.1", server.bound_port)
    try:
        ch = await client.channel()
        await ch.queue_declare("fcst_q")
        received = []
        await ch.basic_consume("fcst_q", received.append, no_ack=True)

        async def load() -> None:
            for _ in range(60):
                for _ in range(20):
                    ch.basic_publish(
                        b"x" * 512, exchange="", routing_key="fcst_q")
                await asyncio.sleep(0.01)

        load_task = asyncio.create_task(load())
        # first round includes the jit compile of the tiny model; allow for it
        deadline = asyncio.get_event_loop().time() + 60
        while forecaster.forecast is None:
            assert asyncio.get_event_loop().time() < deadline, \
                forecaster.last_error
            await asyncio.sleep(0.05)
        await load_task

        snap = forecaster.snapshot()
        assert snap["error"] is None
        assert snap["trained_steps"] > 0
        # the sampler saw the real traffic, not synthetic series (history,
        # not the latest vector: the final tick may land after load stops)
        history = forecaster.ring.history()
        assert history[:, FEATURES.index("publish_rate")].max() > 0
        assert history[:, FEATURES.index("deliver_rate")].max() > 0
        assert snap["samples"] >= 9

        status, body = await _http_get(admin.bound_port, "/admin/forecast")
        assert status.endswith("200 OK")
        payload = json.loads(body)
        assert payload["enabled"] is True
        forecast = payload["forecast"]
        assert set(forecast) == set(FEATURES)
        for name, value in forecast.items():
            assert np.isfinite(value), (name, value)
            assert value >= 0.0
        assert payload["loss"] is not None and np.isfinite(payload["loss"])

        status, body = await _http_get(admin.bound_port, "/metrics")
        assert status.endswith("200 OK")
        text = body.decode()
        assert 'chanamq_forecast{feature="publish_rate"}' in text
        assert "chanamq_forecast_loss" in text
        assert len(received) > 0  # the load actually flowed through
    finally:
        await client.close()
        await forecaster.stop()
        await admin.stop()
        await server.stop()


async def test_admin_forecast_disabled_reports_enabled_false():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        status, body = await _http_get(admin.bound_port, "/admin/forecast")
        assert status.endswith("200 OK")
        assert json.loads(body) == {"enabled": False}
    finally:
        await admin.stop()
        await server.stop()
