"""L2+L3: connection engine and broker entities.

Rebuilds the capability of the reference's chana-mq-server runtime — the
FrameStage protocol engine (engine/FrameStage.scala:53-1297) and the four
sharded entity actors (entity/{Vhost,Exchange,Queue,Message}Entity.scala) —
as an asyncio host runtime: one reader/writer task pair per connection, a
synchronous event-driven dispatch engine per queue (replacing the reference's
1 microsecond tick poll, ServerBluePrint.scala:31), and write-through
persistence hooks with strict FIFO ordering.
"""

from .broker import Broker
from .server import BrokerServer

__all__ = ["Broker", "BrokerServer"]
