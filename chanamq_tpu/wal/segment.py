"""WAL segment files: append-only logs under ``<store>.wal/``.

One shard store directory holds one WAL: an active segment the commit
loop appends to, plus zero or more sealed segments awaiting checkpoint
truncation.  File names carry the first LSN a segment may contain
(``segment-<first_lsn>.log``), so the set orders and scans without any
side index — recovery is a directory listing plus a frame walk.
"""

from __future__ import annotations

import os
import re
from typing import Optional

from .codec import scan_frames

_NAME = re.compile(r"^segment-(\d{20})\.log$")


def segment_name(first_lsn: int) -> str:
    return f"segment-{first_lsn:020d}.log"


def list_segments(dir_path: str) -> "list[tuple[int, str]]":
    """(first_lsn, path) for every segment file, in LSN order."""
    out: list[tuple[int, str]] = []
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return out
    for name in names:
        m = _NAME.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, name)))
    out.sort()
    return out


def read_segment(path: str) -> "tuple[list[bytes], int, str]":
    """Frame-walk one segment file: (payloads, good_bytes, status)."""
    with open(path, "rb") as f:
        data = f.read()
    return scan_frames(data)


def truncate_segment(path: str, good_bytes: int) -> None:
    """Drop a torn tail in place (crash interrupted the final append)."""
    with open(path, "r+b") as f:
        f.truncate(good_bytes)


class SegmentWriter:
    """The active segment: buffered appends + explicit fsync.

    All methods run on the WAL's dedicated writer thread (one commit at
    a time), so no locking is needed here.
    """

    def __init__(self, dir_path: str, first_lsn: int) -> None:
        self.dir = dir_path
        self.first_lsn = first_lsn
        self.last_lsn = first_lsn - 1
        self.path = os.path.join(dir_path, segment_name(first_lsn))
        self._f = open(self.path, "ab")
        self.size = self._f.tell()

    def append(self, data: bytes, last_lsn: int) -> None:
        self._f.write(data)
        self.size += len(data)
        self.last_lsn = last_lsn

    def sync(self, fsync: bool) -> None:
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def roll(self, fsync: bool) -> "SegmentWriter":
        """Seal this segment (flushed + synced) and open the next one."""
        self.sync(fsync)
        self._f.close()
        return SegmentWriter(self.dir, self.last_lsn + 1)

    def close(self, fsync: bool = True) -> None:
        try:
            self.sync(fsync)
        finally:
            self._f.close()


def fsync_dir(dir_path: str) -> None:
    """Make segment create/unlink durable (directory entry fsync)."""
    try:
        fd = os.open(dir_path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def ensure_dir(dir_path: str) -> None:
    os.makedirs(dir_path, exist_ok=True)


def quarantine(path: str) -> Optional[str]:
    """Rename an unreplayable segment aside (evidence, never replayed)."""
    target = path + ".corrupt"
    try:
        os.replace(path, target)
        return target
    except OSError:
        return None
