"""Native hot-path tests: C++ frame scanner + topic trie vs the Python
reference implementations (property/parity testing), plus a speed sanity
check. Skipped when the toolchain can't build the library."""

import random

import pytest

from chanamq_tpu import native_ext
from chanamq_tpu.amqp.constants import FrameType
from chanamq_tpu.amqp.frame import Frame, FrameError, FrameParser, HEARTBEAT_FRAME
from chanamq_tpu.broker.matchers import TopicMatcher

pytestmark = pytest.mark.skipif(
    not native_ext.available(), reason="native library unavailable")


def make_frames(count, seed=0):
    rng = random.Random(seed)
    frames = []
    for _ in range(count):
        ftype = rng.choice([FrameType.METHOD, FrameType.HEADER, FrameType.BODY,
                            FrameType.HEARTBEAT])
        payload = b"" if ftype == FrameType.HEARTBEAT else rng.randbytes(rng.randint(0, 300))
        channel = 0 if ftype == FrameType.HEARTBEAT else rng.randint(0, 100)
        frames.append(Frame(ftype, channel, payload))
    return frames


def test_native_parser_parity_random_chunking():
    frames = make_frames(200, seed=7)
    raw = b"".join(f.to_bytes() for f in frames)
    rng = random.Random(1)
    native, python = native_ext.NativeFrameParser(), FrameParser()
    out_native, out_python = [], []
    i = 0
    while i < len(raw):
        n = rng.randint(1, 701)
        chunk = raw[i : i + n]
        out_native.extend(native.feed(chunk))
        out_python.extend(python.feed(chunk))
        i += n
    assert out_native == out_python == frames


def test_native_parser_error_parity():
    bad_end = bytearray(Frame(FrameType.METHOD, 1, b"xy").to_bytes())
    bad_end[-1] = 0x00
    out = list(native_ext.NativeFrameParser().feed(bytes(bad_end)))
    assert isinstance(out[0], FrameError)
    # garbage rejected from the header alone
    out = list(native_ext.NativeFrameParser().feed(b"\x41" * 12))
    assert isinstance(out[0], FrameError)
    # frame-max enforcement
    parser = native_ext.NativeFrameParser()
    parser.frame_max = 16
    out = list(parser.feed(Frame(FrameType.BODY, 1, b"x" * 64).to_bytes()))
    assert isinstance(out[0], FrameError)
    # dead after error
    assert list(parser.feed(HEARTBEAT_FRAME.to_bytes())) == []


def test_native_parser_frames_before_error_are_delivered():
    good = Frame(FrameType.METHOD, 1, b"ok").to_bytes()
    bad = bytearray(Frame(FrameType.METHOD, 1, b"no").to_bytes())
    bad[-1] = 0x13
    out = list(native_ext.NativeFrameParser().feed(good + bytes(bad)))
    assert out[0] == Frame(FrameType.METHOD, 1, b"ok")
    assert isinstance(out[1], FrameError)


def random_topic_ops(seed, n_ops=400):
    rng = random.Random(seed)
    words = ["a", "b", "c", "stock", "nyse", "*", "#"]
    ops = []
    live = []
    for _ in range(n_ops):
        if live and rng.random() < 0.3:
            ops.append(("unbind", *rng.choice(live)))
        else:
            pattern = ".".join(rng.choice(words) for _ in range(rng.randint(1, 4)))
            queue = f"q{rng.randint(0, 20)}"
            ops.append(("bind", pattern, queue))
            live.append((pattern, queue))
    return ops


def test_native_trie_parity_randomized():
    rng = random.Random(42)
    key_words = ["a", "b", "c", "stock", "nyse", "x"]
    for seed in range(5):
        native, python = native_ext.NativeTopicMatcher(), TopicMatcher()
        for op in random_topic_ops(seed):
            kind, pattern, queue = op
            if kind == "bind":
                assert native.bind(pattern, queue) == python.bind(pattern, queue)
            else:
                assert native.unbind(pattern, queue) == python.unbind(pattern, queue)
        for _ in range(200):
            key = ".".join(rng.choice(key_words)
                           for _ in range(rng.randint(1, 5)))
            assert native.route(key) == python.route(key), (seed, key)
        assert native.bindings() == python.bindings()


def test_native_trie_wildcards():
    m = native_ext.NativeTopicMatcher()
    m.bind("stock.*.nyse", "q1")
    m.bind("stock.#", "q2")
    m.bind("#", "q3")
    assert m.route("stock.ibm.nyse") == {"q1", "q2", "q3"}
    assert m.route("stock") == {"q2", "q3"}
    assert m.route("bond") == {"q3"}
    m.unbind_queue("q2")
    assert m.route("stock.ibm.nyse") == {"q1", "q3"}


def test_native_trie_unbind_prunes():
    m = native_ext.NativeTopicMatcher()
    m.bind("a.b.c", "q1")
    assert m.unbind("a.b.c", "q1")
    assert not m.unbind("a.b.c", "q1")
    assert m.route("a.b.c") == set()


def test_native_faster_than_python_parser():
    """Sanity check, not a benchmark: the native scanner should beat the
    Python loop on a large frame stream."""
    import time

    frames = make_frames(2000, seed=3)
    raw = b"".join(f.to_bytes() for f in frames)

    t0 = time.perf_counter()
    for _ in range(5):
        assert sum(1 for _ in FrameParser().feed(raw)) == 2000
    t_python = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(5):
        assert sum(1 for _ in native_ext.NativeFrameParser().feed(raw)) == 2000
    t_native = time.perf_counter() - t0
    # be generous (CI noise): just require it not be slower
    assert t_native < t_python * 1.1, (t_native, t_python)


def test_native_trie_route_grows_past_buffer():
    """The route result buffer starts at 4096; a fanout-wide topic binding
    set larger than that must return EVERY queue, not a truncated set
    (regression: silent truncation flagged in rounds 1-2)."""
    m = native_ext.NativeTopicMatcher()
    n = 5000
    for i in range(n):
        m.bind("wide.key", f"q{i}")
    out = m.route("wide.key")
    assert len(out) == n
    assert out == {f"q{i}" for i in range(n)}
