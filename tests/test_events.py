"""Event bus + firehose: system-exchange lifecycle, O(1) unbound drops,
end-to-end consumption of internal events, firehose ordering/recursion
exclusions and flow-stage shedding, and cross-run determinism mod ts.

Module-gate hygiene: every test that installs the bus/firehose clears the
``events`` globals in a finally block — leaked gates would tap unrelated
tests' traffic.
"""

import asyncio
import json
from types import SimpleNamespace

import pytest

from chanamq_tpu import events
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.events import EVENT_EXCHANGE, TRACE_EXCHANGE, EventBus, Firehose
from chanamq_tpu.rest.admin import AdminServer

pytestmark = pytest.mark.asyncio


async def _server():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    return server


async def http_req(port: int, path: str) -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), json.loads(body) if body else {}


# ---------------------------------------------------------------------------
# system exchanges: predeclared, reserved
# ---------------------------------------------------------------------------


async def test_system_exchanges_predeclared_and_reserved():
    server = await _server()
    try:
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)

        # both system exchanges exist on the default vhost (passive ok)
        ch = await c.channel()
        await ch.exchange_declare(EVENT_EXCHANGE, passive=True)
        await ch.exchange_declare(TRACE_EXCHANGE, passive=True)

        # clients cannot (re)declare them: access-refused, channel closed
        with pytest.raises(ChannelClosedError) as exc:
            await ch.exchange_declare(EVENT_EXCHANGE, "topic")
        assert exc.value.reply_code == 403

        # ...nor delete them
        ch2 = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch2.exchange_delete(EVENT_EXCHANGE)
        assert exc.value.reply_code == 403
        ch3 = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch3.exchange_delete(TRACE_EXCHANGE)
        assert exc.value.reply_code == 403

        # but binding to them is ordinary Queue.Bind
        ch4 = await c.channel()
        await ch4.queue_declare("evq")
        await ch4.queue_bind("evq", EVENT_EXCHANGE, "alert.#")
        await c.close()
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# emission: O(1) drop unbound, envelope, end-to-end consume
# ---------------------------------------------------------------------------


async def test_emit_with_nothing_bound_is_o1_drop():
    server = await _server()
    try:
        broker = server.broker
        bus = EventBus(broker)
        m = broker.metrics
        before_pub = m.events_published_total
        assert bus.emit("alert.fired.x", {"rule": "x"}) is False
        assert m.events_dropped_total == 1
        assert m.events_published_total == before_pub
        # no message was built: seq never advanced, no queue grew
        assert bus.seq == 0
        assert broker.queue_depth == 0
    finally:
        await server.stop()


async def test_event_consume_end_to_end_envelope_wins():
    server = await _server()
    try:
        bus = EventBus(server.broker)
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("evq")
        await ch.queue_bind("evq", EVENT_EXCHANGE, "alert.#")
        got: list = []
        done = asyncio.Event()

        def on_msg(msg):
            got.append(msg)
            done.set()

        await ch.basic_consume("evq", on_msg, no_ack=True)

        # the alert payload carries its own "event" key ("fired") — the
        # envelope's routing-key stamp must win
        assert bus.emit("alert.fired.deep",
                        {"event": "fired", "rule": "deep"}) is True
        await asyncio.wait_for(done.wait(), 5)
        msg = got[0]
        assert msg.exchange == EVENT_EXCHANGE
        assert msg.routing_key == "alert.fired.deep"
        assert msg.properties.content_type == "application/json"
        assert msg.properties.app_id == "chanamq.events"
        body = json.loads(msg.body)
        assert body["event"] == "alert.fired.deep"
        assert body["rule"] == "deep"
        assert body["seq"] == 1 and body["node"] == "local"
        assert "ts" in body

        # a key outside the binding is dropped, not queued
        dropped_before = server.broker.metrics.events_dropped_total
        assert bus.emit("control.decision.scale", {"kind": "scale"}) is False
        assert server.broker.metrics.events_dropped_total == dropped_before + 1
        await c.close()
    finally:
        await server.stop()


async def test_queue_lifecycle_events_through_installed_bus():
    server = await _server()
    try:
        events.install(EventBus(server.broker))
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sink")
        await ch.queue_bind("sink", EVENT_EXCHANGE, "queue.#")
        got: list = []

        def on_msg(msg):
            got.append(json.loads(msg.body))

        await ch.basic_consume("sink", on_msg, no_ack=True)
        await ch.queue_declare("watched", durable=True)
        await ch.queue_delete("watched")
        await asyncio.sleep(0.2)
        kinds = [(e["event"], e["queue"]) for e in got]
        assert ("queue.declared", "watched") in kinds
        assert ("queue.deleted", "watched") in kinds
        declared = next(e for e in got if e["event"] == "queue.declared"
                        and e["queue"] == "watched")
        assert declared["durable"] is True and declared["vhost"] == "/"
        await c.close()
    finally:
        events.clear()
        await server.stop()


# ---------------------------------------------------------------------------
# firehose: ordering, recursion exclusion, stage shedding
# ---------------------------------------------------------------------------


async def test_firehose_preserves_confirms_and_never_taps_itself():
    server = await _server()
    try:
        broker = server.broker
        events.install(None, Firehose(broker))
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("wq")
        await ch.queue_declare("tap")
        await ch.queue_bind("tap", TRACE_EXCHANGE, "#")

        # confirm ordering: N publishes through the tapped path must
        # confirm in publish order
        pub = await c.channel()
        await pub.confirm_select()
        order: list = []
        for i in range(20):
            seq = pub.basic_publish(f"m{i}".encode(), routing_key="wq")
            fut = asyncio.get_event_loop().create_future()
            pub._confirm_waiters[seq] = fut
            fut.add_done_callback(lambda _f, s=seq: order.append(s))
        await pub.wait_unconfirmed_below(1)
        await asyncio.sleep(0.1)
        assert order == sorted(order) and len(order) == 20

        # consume the work queue so deliver.<queue> taps flow too
        got_wq = asyncio.Event()
        n_wq = 0

        def on_wq(msg):
            nonlocal n_wq
            n_wq += 1
            if n_wq == 20:
                got_wq.set()

        await ch.basic_consume("wq", on_wq, no_ack=True)
        await asyncio.wait_for(got_wq.wait(), 5)

        # drain the tap queue (its own deliveries must NOT re-tap)
        taps: list = []

        def on_tap(msg):
            taps.append(msg)

        await ch.basic_consume("tap", on_tap, no_ack=True)
        await asyncio.sleep(0.3)

        keys = [t.routing_key for t in taps]
        assert keys.count("publish") == 20       # default exchange -> bare
        assert keys.count("deliver.wq") == 20
        # no recursion: nothing tapped from the system exchanges
        assert not [k for k in keys
                    if k.startswith(("publish.amq.chanamq",
                                     "deliver.tap"))]
        # counters settled exactly: 20 publish taps + 20 deliver taps,
        # and draining the tap queue added nothing
        published = broker.metrics.firehose_published_total
        assert published == 40
        await asyncio.sleep(0.2)
        assert broker.metrics.firehose_published_total == published
        # tap headers carry the provenance
        hdr = taps[0].properties.headers
        assert hdr["node"] == "local" and "routing_key" in hdr
        await c.close()
    finally:
        events.clear()
        await server.stop()


async def test_firehose_sheds_when_flow_stage_raised():
    server = await _server()
    try:
        broker = server.broker
        fh = Firehose(broker)
        events.install(None, fh)
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("wq")
        await ch.queue_declare("tap")
        await ch.queue_bind("tap", TRACE_EXCHANGE, "publish.#")

        # stub accountant: stage > 0 sheds; components/reevaluate satisfy
        # account_memory's synchronous pushes on the publish path
        broker.flow = SimpleNamespace(
            stage=1, components={}, reevaluate=lambda: None)
        dropped = broker.metrics.firehose_dropped_total
        ch.basic_publish(b"x", routing_key="wq")
        await asyncio.sleep(0.1)
        assert broker.metrics.firehose_dropped_total == dropped + 1
        assert broker.metrics.firehose_published_total == 0

        broker.flow = None  # stage cleared: taps resume
        ch.basic_publish(b"y", routing_key="wq")
        await asyncio.sleep(0.1)
        assert broker.metrics.firehose_published_total == 1
        await c.close()
    finally:
        events.clear()
        await server.stop()


async def test_firehose_idle_gate_tracks_trace_bindings():
    """The hot-path seams gate on ``tap_bindings`` — the trace matcher's
    live binding table. It must be resolved at construction, stay falsy
    while nothing is bound (enabled-but-unconsumed firehose = free), and
    flip truthy/falsy as tap queues bind and die, without re-resolution
    (the alias is the same object the matcher mutates)."""
    server = await _server()
    try:
        fh = Firehose(server.broker)
        assert fh.tap_bindings is not None and not fh.tap_bindings
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("tap")
        await ch.queue_bind("tap", TRACE_EXCHANGE, "#")
        assert fh.tap_bindings
        await ch.queue_delete("tap")  # unbinds everywhere, table drains
        assert not fh.tap_bindings
        await c.close()
    finally:
        await server.stop()


async def test_firehose_queue_filter():
    server = await _server()
    try:
        events.install(None, Firehose(server.broker, queue_filter="keep"))
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("keep-me")
        await ch.queue_declare("skip-me")
        await ch.queue_declare("tap")
        await ch.queue_bind("tap", TRACE_EXCHANGE, "#")
        ch.basic_publish(b"a", routing_key="keep-me")
        ch.basic_publish(b"b", routing_key="skip-me")
        await asyncio.sleep(0.1)
        assert server.broker.metrics.firehose_published_total == 1
        await c.close()
    finally:
        events.clear()
        await server.stop()


# ---------------------------------------------------------------------------
# determinism + admin surface
# ---------------------------------------------------------------------------


async def _scripted_run() -> list[dict]:
    """One broker, a scripted op sequence, the consumed event bodies."""
    server = await _server()
    try:
        events.install(EventBus(server.broker))
        c = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sink")
        for key in ("queue.#", "alert.#", "flow.#"):
            await ch.queue_bind("sink", EVENT_EXCHANGE, key)
        got: list = []

        def on_msg(msg):
            got.append(json.loads(msg.body))

        await ch.basic_consume("sink", on_msg, no_ack=True)
        await ch.queue_declare("q1")
        events.ACTIVE.emit("alert.fired.backlog", {"rule": "backlog"})
        events.ACTIVE.emit("flow.stage.2", {"stage": 2, "label": "throttle"})
        await ch.queue_delete("q1")
        events.ACTIVE.emit("alert.cleared.backlog", {"rule": "backlog"})
        await asyncio.sleep(0.2)
        await c.close()
        return got
    finally:
        events.clear()
        await server.stop()


async def test_event_stream_deterministic_mod_ts():
    """Two identical runs produce identical event sequences once wall-
    clock ``ts`` is masked — seq, keys, payloads and order all match."""
    first = await _scripted_run()
    second = await _scripted_run()

    def mask(evs):
        return [{k: v for k, v in e.items() if k != "ts"} for e in evs]

    assert len(first) == 5
    assert mask(first) == mask(second)
    assert [e["seq"] for e in first] == [1, 2, 3, 4, 5]


async def test_admin_events_endpoint():
    server = await _server()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        status, body = await http_req(admin.bound_port, "/admin/events")
        assert status == 200
        assert body["enabled"] is False and body["firehose_enabled"] is False

        events.install(EventBus(server.broker), Firehose(server.broker))
        server.broker.metrics.events_dropped_total += 3
        status, body = await http_req(admin.bound_port, "/admin/events")
        assert status == 200
        assert body["enabled"] is True and body["firehose_enabled"] is True
        assert body["events"]["dropped"] == 3
        assert body["bus"]["exchange"] == EVENT_EXCHANGE
    finally:
        events.clear()
        await admin.stop()
        await server.stop()
