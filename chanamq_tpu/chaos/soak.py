"""The chaos soak: a 3-node replicated workload under a seeded fault plan.

Shared by ``bench.py --chaos`` and ``tests/test_chaos.py`` so the tier-1
smoke and the test suite assert the same invariants:

1. **No confirmed message lost** — every body whose publisher confirm
   arrived is delivered to the consumer at least once.
2. **No double-delivery after settle** — duplicates during failover are
   at-least-once reality and merely counted; once the workload settles
   (everything delivered, surviving owner's queue empty, observation
   window passed) no further delivery may arrive.
3. **Exactly one failover promotion** — the owner crash promotes exactly
   one replica, cluster-wide.
4. **Cursors resume at committed offsets** — a stream consumer that
   detaches and reattaches at "next" resumes at committed+1 and reads
   contiguously to the tail.
5. **Reconnect stays inside the backoff budget** — the publisher finishes
   every message despite injected disconnects/partitions, and no stream's
   backoff delay ever exceeds the configured ceiling.
6. **Health gates and alerts are deterministic** — both nodes must report
   ready (telemetry/health.py) before any load is offered, and a scripted
   backlog + stalled-consumer phase on the surviving node must fire
   exactly the expected alert rules: the telemetry services are
   tick-driven by the harness (no timers), so the alert engine sees the
   same series every run and the firing set is exact, like the fault
   schedule itself.

Topology: three nodes A, B, C with private stores (MemoryStore by
default; ``wal=True`` gives every node a WAL-fronted SQLite store so the
group-fsync confirm gate sits in the durability path under chaos),
replicate factor 2, sync confirms. Queue ``rq`` is owned by A with its
replica placed on B, but published AND consumed via B, so every message
crosses the data plane twice (push B->A, deliver A->B) and every confirm
gates on A's mutation-log ship back to B. Mid-run a crash rule kills A;
B must promote its replica and finish the workload locally while C looks
on — exactly one promotion cluster-wide (the replica holder), but BOTH
survivors observe the DOWN and re-hash the ring once each. The stream
queue lives on B (replica on C) and survives the crash.

Determinism: the publisher consults the plan once per message at the
``soak.tick`` site, so the crash fires at a fixed publish index for a
given seed. Transport-site rules use invocation windows, making their
schedule a pure function of the seed as well (see plan.py).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from . import ChaosStore, FaultPlan, FaultRule, _LazyRuntime, clear, install

# logical crash-target name the plan uses; the harness maps it to node A
CRASH_TARGET = "owner"

BACKOFF_BUDGET_S = 5.0  # ReconnectBackoff max_s: no delay may exceed it


def default_plan(seed: int, owner: str, messages: int) -> FaultPlan:
    """The full seeded soak: partitions + node crash + slow store +
    transport latency/disconnects. Windows are invocation-indexed so the
    schedule is deterministic per seed; the crash rides the publisher's
    ``soak.tick`` so it lands at a fixed publish index. Transport faults
    that can strand state on A (lost settles, dropped deliver batches)
    are windowed BEFORE the crash: failover requeues them from B's
    replica, which is exactly the recovery the soak must prove."""
    crash_at = max(10, int(messages * 0.55))
    return FaultPlan(seed, [
        FaultRule(name="crash-owner", kind="crash", sites=["soak.tick"],
                  after=crash_at, count=1, nodes=[CRASH_TARGET]),
        FaultRule(name="partition-to-owner", kind="partition",
                  sites=["data.send"], nodes=[owner], after=20, until=45),
        FaultRule(name="drop-deliver", kind="drop", sites=["data.event"],
                  count=2, after=5, until=crash_at),
        FaultRule(name="disconnect-data", kind="disconnect",
                  sites=["data.read"], probability=0.05, count=2,
                  until=crash_at),
        FaultRule(name="wire-latency", kind="latency",
                  sites=["data.send", "rpc.call"], probability=0.05,
                  delay_ms=3),
        FaultRule(name="slow-store", kind="latency", sites=["store.flush"],
                  probability=0.3, delay_ms=8),
    ])


async def run_soak(
    seed: int, *, messages: int = 160, stream_records: int = 40,
    plan: Optional[FaultPlan] = None, metrics_sink=None,
    uds: bool = False, wal: bool = False,
) -> dict:
    """Run the workload under the plan; returns a report whose
    ``violations`` list is empty iff every invariant held.

    ``uds=True`` runs the interconnect over Unix-domain sockets — the
    exact transport sibling shards use (shard/) — so the crash becomes
    the shard-crash drill: same plan, same invariants, plus ownership
    re-hashes observed by each survivor.

    ``wal=True`` backs every node with a WAL-fronted SQLite store
    (wal/engine.py over a private temp dir): confirms then gate on the
    cross-channel group fsync, and the slow-store rule stalls the WAL
    commit barrier itself — proving the no-confirmed-loss invariant with
    the real durability engine in the path, not a memory stand-in."""
    import os
    import shutil
    import tempfile

    from ..amqp.properties import BasicProperties
    from ..client.client import AMQPClient
    from ..store.memory import MemoryStore
    from ..broker.server import BrokerServer
    from ..cluster.node import ClusterNode
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults

    uds_dir = tempfile.mkdtemp(prefix="chanamq-soak-") if uds else None
    wal_dir = tempfile.mkdtemp(prefix="chanamq-soak-wal-") if wal else None
    wal_count = 0

    def make_store():
        if not wal:
            return MemoryStore()
        nonlocal wal_count
        from ..store.sqlite import SqliteStore
        from ..wal import WalStore
        wal_count += 1
        path = os.path.join(wal_dir, f"node{wal_count}.db")
        return WalStore(SqliteStore(path), flush_ms=1.0, checkpoint_ms=200.0)

    async def start_node(seeds, uds_path=None):
        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=make_store())
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                         heartbeat_interval_s=0.2, failure_timeout_s=1.5,
                         replicate_factor=2, replicate_sync=True,
                         replicate_ack_timeout_ms=2000,
                         uds_path=uds_path)
        await cl.start()
        # tick-driven telemetry: the harness calls sample_tick at scripted
        # points instead of starting the timer task, so the alert engine's
        # input series — and therefore its firings — are exact. Node-scoped
        # rules get unreachable thresholds (loop lag and replication lag
        # depend on host timing, which would make firings flaky).
        srv.broker.telemetry = TelemetryService(
            srv.broker, interval_s=1.0, ring_ticks=64,
            rules=alert_defaults(
                backlog_growth=50.0, backlog_window=5, stall_ticks=3,
                repl_lag=1e12, loop_lag_ms=1e12))
        return srv, cl

    a_srv = a_cl = b_srv = b_cl = c_srv = c_cl = None
    conns: list = []
    violations: list[str] = []
    try:
        a_path = os.path.join(uds_dir, "a.sock") if uds_dir else None
        b_path = os.path.join(uds_dir, "b.sock") if uds_dir else None
        c_path = os.path.join(uds_dir, "c.sock") if uds_dir else None
        a_srv, a_cl = await start_node([], uds_path=a_path)
        b_srv, b_cl = await start_node([a_cl.name], uds_path=b_path)
        c_srv, c_cl = await start_node([a_cl.name], uds_path=c_path)
        if uds:
            # ephemeral cluster ports: names exist only after start, so
            # the sibling map is patched in afterwards (real shards use
            # fixed base+index ports and get the map at construction)
            for cl, path in ((a_cl, a_path), (b_cl, b_path), (c_cl, c_path)):
                for other, opath in ((a_cl, a_path), (b_cl, b_path),
                                     (c_cl, c_path)):
                    if other is not cl:
                        cl.uds_map[other.name] = opath
        clusters = (a_cl, b_cl, c_cl)
        for _ in range(100):
            if all(len(cl.membership.alive_members()) == 3
                   for cl in clusters):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("3-node membership did not converge")

        # -- health gate (invariant 6a): all nodes ready before any load
        health_gate: dict[str, bool] = {}
        for srv, cl in ((a_srv, a_cl), (b_srv, b_cl), (c_srv, c_cl)):
            srv.broker.telemetry.sample_tick(1.0)
            health = srv.broker.telemetry.health()
            health_gate[cl.name] = health["ready"]
            if not health["ready"]:
                violations.append(
                    f"health gate: {cl.name} not ready before load: "
                    f"{health['reasons']}")

        # placement is pinned, not just ownership: rq's replica must sit
        # on B (the consumer's node) so the crash promotes where the
        # consumer already is, and sq's on C so the stream's sync-confirm
        # path never gates on the dead node
        def placed(prefix, owner, replica):
            return next(
                f"{prefix}{i}" for i in range(2000)
                if a_cl.ring.preference_entity("q", "/", f"{prefix}{i}", 2)
                == [owner.name, replica.name])

        rq = placed("cq", a_cl, b_cl)
        sq = placed("cs", b_cl, c_cl)

        if plan is None:
            plan = default_plan(seed, a_cl.name, messages)
        runtime = install(plan, metrics=metrics_sink or b_srv.broker.metrics)
        fingerprint = plan.fingerprint()
        # store seams on both nodes (the slow-store rule hits the flush
        # barrier); the lazy shim keeps them live across install/clear
        a_srv.broker.store = ChaosStore(a_srv.broker.store, _LazyRuntime())
        b_srv.broker.store = ChaosStore(b_srv.broker.store, _LazyRuntime())
        c_srv.broker.store = ChaosStore(c_srv.broker.store, _LazyRuntime())

        crashed = asyncio.Event()

        def crash_owner() -> None:
            async def _die():
                # abrupt stop: no drain ordering — B must detect the
                # silence (no leave protocol) and promote
                for part in (a_cl, a_srv):
                    try:
                        await part.stop()
                    except Exception:
                        pass
                crashed.set()
            asyncio.get_event_loop().create_task(_die())

        runtime.on_crash(CRASH_TARGET, crash_owner)

        # -- consumer on B (remote consumer of A's queue, then local
        #    consumer of the promoted replica after the crash)
        persistent = BasicProperties(delivery_mode=2)
        deliveries: dict[str, int] = {}
        settle_mark = asyncio.Event()
        post_settle: list[str] = []
        delivered_event = asyncio.Event()

        c_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        conns.append(c_conn)
        c_ch = await c_conn.channel()
        await c_ch.basic_qos(prefetch_count=64)

        def on_msg(msg):
            body = bytes(msg.body).decode()
            deliveries[body] = deliveries.get(body, 0) + 1
            if settle_mark.is_set():
                post_settle.append(body)
            c_ch.basic_ack(msg.delivery_tag)
            delivered_event.set()

        p_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        conns.append(p_conn)
        p_ch = await p_conn.channel()
        await p_ch.confirm_select()
        await p_ch.queue_declare(rq, durable=True)
        for _ in range(100):
            if ("/", rq) in b_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        await c_ch.basic_consume(rq, on_msg, consumer_tag="soak-consumer")

        # -- publisher: one confirm-gated message at a time, reconnecting
        #    through aborts/partitions; soak.tick drives the crash index
        confirmed: set[int] = set()
        attempts = 0
        max_backoff_seen = 0.0

        def observe_backoff() -> None:
            nonlocal max_backoff_seen
            for cl in (b_cl,):
                for plane in cl._dataplanes.values():
                    for st in plane.stats()["backoff"]:
                        max_backoff_seen = max(max_backoff_seen,
                                               st["delay_s"])

        async def reconnect_publisher():
            nonlocal p_conn, p_ch
            try:
                await p_conn.close()
            except Exception:
                pass
            p_conn = await AMQPClient.connect("127.0.0.1",
                                              b_srv.bound_port)
            conns.append(p_conn)
            p_ch = await p_conn.channel()
            await p_ch.confirm_select()

        for i in range(messages):
            runtime.decide("soak.tick")  # deterministic crash index
            body = f"m{i:06d}".encode()
            for attempt in range(60):
                attempts += 1
                try:
                    await p_ch.basic_publish_confirmed(
                        body, routing_key=rq, properties=persistent,
                        timeout=8)
                    confirmed.add(i)
                    break
                except Exception:
                    observe_backoff()
                    await asyncio.sleep(0.25)
                    try:
                        await reconnect_publisher()
                    except Exception:
                        pass  # next attempt retries the dial
            else:
                violations.append(
                    f"publish m{i:06d} never confirmed within the "
                    f"reconnect budget")
                break
        observe_backoff()

        # -- drain: every confirmed body delivered at least once, then the
        #    surviving owner's queue runs empty (requeued strays included)
        want = {f"m{i:06d}" for i in confirmed}

        def surviving_queue():
            for srv in (b_srv, c_srv, a_srv):
                if srv is None:
                    continue
                vhost = srv.broker.vhosts.get("/")
                queue = vhost.queues.get(rq) if vhost else None
                if queue is not None and queue.consumer_count:
                    return queue
            return None

        deadline = asyncio.get_event_loop().time() + 45
        while asyncio.get_event_loop().time() < deadline:
            queue = surviving_queue()
            if (want <= set(deliveries) and queue is not None
                    and queue.message_count == 0
                    and not queue.outstanding):
                break
            delivered_event.clear()
            try:
                await asyncio.wait_for(delivered_event.wait(), 0.25)
            except asyncio.TimeoutError:
                pass
        missing = sorted(want - set(deliveries))
        if missing:
            violations.append(
                f"confirmed-but-lost: {len(missing)} messages "
                f"(first: {missing[:5]})")

        # -- settle: duplicates beyond this point violate invariant 2
        settle_mark.set()
        await asyncio.sleep(0.7)
        duplicates = sum(n - 1 for n in deliveries.values() if n > 1)
        if post_settle:
            violations.append(
                f"{len(post_settle)} deliveries after settle "
                f"(first: {post_settle[:5]})")

        # -- promotion accounting (A's metrics survive its stop)
        promotions = (a_srv.broker.metrics.repl_promotions
                      + b_srv.broker.metrics.repl_promotions
                      + c_srv.broker.metrics.repl_promotions)
        # ownership re-hash accounting: each DOWN event a node observes
        # re-hashes the ring once and bumps shard_handoffs; with 3 nodes
        # BOTH survivors observe the crash (one re-hash each), but only
        # the replica holder (B) promotes — so a crash run must show
        # exactly two re-hashes and exactly one promotion cluster-wide,
        # and a clean run none of either
        handoffs = (a_srv.broker.metrics.shard_handoffs
                    + b_srv.broker.metrics.shard_handoffs
                    + c_srv.broker.metrics.shard_handoffs)
        expect_crash = any(r.kind == "crash" for r in plan.rules)
        if expect_crash:
            if not crashed.is_set():
                violations.append("crash rule never fired")
            if promotions != 1:
                violations.append(
                    f"expected exactly 1 promotion, saw {promotions}")
            if handoffs != 2:
                violations.append(
                    f"expected exactly 2 ownership re-hashes "
                    f"(one per survivor), saw {handoffs}")
        else:
            if promotions:
                violations.append(f"unexpected promotion(s): {promotions}")
            if handoffs:
                violations.append(
                    f"unexpected ownership re-hash(es): {handoffs}")

        if max_backoff_seen > BACKOFF_BUDGET_S:
            violations.append(
                f"backoff delay {max_backoff_seen:.2f}s exceeded the "
                f"{BACKOFF_BUDGET_S}s budget")

        # -- stream cursor resume (on B, which survived)
        stream = await _stream_cursor_check(
            b_srv, sq, stream_records, violations)

        # -- key-shared group ordering through a member disconnect (on B)
        key_shared = await _key_shared_group_check(
            b_srv, placed("ks", b_cl, c_cl), violations)

        # -- deterministic alert firings (invariant 6b) on the survivor
        alerts = await _alert_phase(b_srv, b_cl, violations)

        return {
            "seed": seed,
            "fingerprint": fingerprint,
            "nodes": 3,
            "store": "wal+sqlite" if wal else "memory",
            "replicate_factor": 2,
            "messages": messages,
            "confirmed": len(confirmed),
            "publish_attempts": attempts,
            "delivered_unique": len(set(deliveries) & want),
            "duplicates": duplicates,
            "post_settle_duplicates": len(post_settle),
            "promotions": promotions,
            "handoffs": handoffs,
            "interconnect": "uds" if uds else "tcp",
            "crashed": crashed.is_set(),
            "max_backoff_s": round(max_backoff_seen, 3),
            "stream": stream,
            "key_shared": key_shared,
            "health_gate": health_gate,
            "alerts": alerts,
            "chaos": runtime.status(),
            "violations": violations,
        }
    finally:
        clear()
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        for part in (c_cl, c_srv, b_cl, b_srv, a_cl, a_srv):
            if part is not None:
                try:
                    await part.stop()
                except Exception:
                    pass
        if wal_dir is not None:
            shutil.rmtree(wal_dir, ignore_errors=True)


# the scripted alert phase must fire exactly these rules, every run
EXPECTED_ALERT_RULES = ("backlog-growth", "consumer-stall")

# the overload soak's scripted pressure phase must fire exactly this rule
OVERLOAD_ALERT_RULES = ("memory-pressure",)


def overload_plan(seed: int, *, pre_ticks: int = 20,
                  pressure_ticks: int = 80,
                  inflate_bytes: int = 3_900_000) -> FaultPlan:
    """The overload soak's fault plan: one ``pressure`` rule riding the
    broker's ``flow.tick`` sweep site. The window is invocation-indexed
    (sweep tick N), so for a given plan the accountant sees the same
    inflation series every run: zero for ``pre_ticks`` ticks, then
    ``inflate_bytes`` for ``pressure_ticks`` ticks, then zero again.
    The default inflation sits between the refuse watermark and the hard
    limit of the soak's broker, so the ladder jumps straight to the
    refuse stage and the headroom left for real accounted bytes is what
    the peak-under-hard-limit invariant exercises."""
    return FaultPlan(seed, [
        FaultRule(name="memory-pressure", kind="pressure",
                  sites=["flow.tick"], after=pre_ticks,
                  until=pre_ticks + pressure_ticks,
                  inflate_bytes=inflate_bytes),
    ])


async def run_overload_soak(
    seed: int, *, messages: int = 160, body_bytes: int = 1024,
    plan: Optional[FaultPlan] = None,
) -> dict:
    """Single-node overload soak: a deterministic memory-pressure chaos
    rule drives the flow ladder to the refuse stage while a flooding
    publisher hammers the broker at far beyond the consumer's drain rate.
    Returns a report whose ``violations`` list is empty iff:

    1. **Accounted bytes never exceed the hard limit** — the ladder's
       whole point: paging + throttling + refusal keep the accountant's
       peak (chaos inflation included) under ``flow.hard-limit``.
    2. **Zero confirmed-message loss** — every body whose publisher
       confirm arrived is delivered, refusals and channel closes
       notwithstanding (a refused publish is never confirmed).
    3. **Publishes are actually refused at the refuse stage** (406
       PRECONDITION_FAILED channel close) while the attached consumer
       keeps draining the backlog.
    4. **channel.flow stop/resume round-trips on the wire** — the
       well-behaved publisher sees exactly Flow(active=False) on
       escalation and Flow(active=True) on recovery, and publishes its
       remaining quota after the resume.
    5. **Full recovery to the low watermark** — once the pressure window
       closes, the ladder cascades back to stage 0 and the accounted
       total settles at/below the low watermark.
    6. **Deterministic alerting and readiness** — the harness-ticked
       telemetry fires exactly ``memory-pressure`` (and resolves it),
       and /admin/health readiness drops only during the refuse stage.
    """
    import time

    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..flow import STAGE_REFUSE, STAGE_THROTTLE
    from ..store.memory import MemoryStore
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults

    broker = Broker(
        store=MemoryStore(),
        message_sweep_interval_s=0.05,    # fast flow ticks for the soak
        queue_max_resident=8,             # base passivation stays on
        flow_high_watermark=128 * 1024,
        flow_hard_limit=4 * 1024 * 1024,  # refuse = 90% of this
        flow_page_resident=2,             # stage>=1 pages queues to 2 bodies
        flow_publish_credit=16 * 1024,
        flow_consumer_buffer=4 * 1024 * 1024,
    )
    flow = broker.flow
    if plan is None:
        plan = overload_plan(seed)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                       heartbeat_s=0)
    # harness-ticked telemetry: every rule except memory-pressure gets an
    # unreachable threshold so the firing set is a pure function of the
    # scripted pressure window
    broker.telemetry = TelemetryService(
        broker, interval_s=1.0, ring_ticks=64,
        rules=alert_defaults(backlog_growth=1e12, stall_ticks=10**6,
                             repl_lag=1e12, loop_lag_ms=1e12,
                             memory_stage=3.5))
    svc = broker.telemetry

    # throttle episode wall-clock, observed at the broker's own ladder
    throttle_t: dict[str, float] = {}

    def stage_watch(old: int, new: int) -> None:
        if new >= STAGE_THROTTLE and old < STAGE_THROTTLE:
            throttle_t.setdefault("start", time.perf_counter())
        if new < STAGE_THROTTLE <= old:
            throttle_t["end"] = time.perf_counter()

    flow.listeners.append(stage_watch)

    violations: list[str] = []
    conns: list = []
    qn = "overload_q"
    pad = b"x" * body_bytes
    phase_a = min(64, max(8, messages // 3))
    phase_resume = min(32, max(4, messages // 5))
    p2_count = max(1, messages - phase_a - phase_resume)

    async def wait_for(predicate, timeout: float, what: str) -> bool:
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate():
            if asyncio.get_event_loop().time() > deadline:
                violations.append(f"timeout waiting for {what}")
                return False
            await asyncio.sleep(0.01)
        return True

    try:
        await srv.start()
        runtime = install(plan, metrics=broker.metrics)
        fingerprint = plan.fingerprint()

        # -- event bus + SLO engine (the observability demo): an AMQP
        #    consumer on amq.chanamq.event watches the ladder escalate
        #    (flow.stage.*), the memory-pressure alert fire, and the
        #    readiness SLO burn/clear — all as ordinary messages. The SLO
        #    spec's windows are tiny because the harness drives exactly 2
        #    not-ready ticks at the refuse stage and 4 ready ticks after
        #    recovery: both pairs must fire at the stage and clear by the
        #    final tick, every run.
        import json as json_mod

        from .. import events as events_mod
        from ..slo import SLOEngine, SLOSpec

        svc.set_slo(SLOEngine([SLOSpec(
            "readiness", "readiness", objective=0.999,
            fast_windows=(2, 4), slow_windows=(4, 8),
            fast_burn=10.0, slow_burn=10.0, budget_window=64)]))
        ev_conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(ev_conn)
        ev_ch = await ev_conn.channel()
        await ev_ch.queue_declare("ovl-events")
        for pattern in ("flow.#", "alert.#", "slo.#"):
            await ev_ch.queue_bind("ovl-events", "amq.chanamq.event",
                                   pattern)
        observed_events: list[str] = []

        def on_bus(msg):
            observed_events.append(json_mod.loads(bytes(msg.body))["event"])
            ev_ch.basic_ack(msg.delivery_tag)

        await ev_ch.basic_consume("ovl-events", on_bus,
                                  consumer_tag="ovl-events")
        events_mod.install(events_mod.EventBus(broker))

        deliveries: dict[bytes, int] = {}

        # -- well-behaved publisher P1: floods a backlog before the
        #    pressure window, then honors channel.flow
        p1 = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(p1)
        p1_ch = await p1.channel()
        await p1_ch.confirm_select()
        await p1_ch.queue_declare(qn)
        for i in range(phase_a):
            p1_ch.basic_publish(b"p1-%05d" % i + pad, routing_key=qn)
        await p1_ch.wait_unconfirmed_below(1, timeout=15)
        confirmed: set[bytes] = {b"p1-%05d" % i for i in range(phase_a)}

        # -- the pressure window opens: the ladder must jump to refuse
        await wait_for(lambda: flow.stage >= STAGE_REFUSE, 15,
                       "refuse stage under chaos pressure")
        stage4_total = flow.total

        # readiness drops only now, with the stage as the reason
        svc.sample_tick(1.0)
        svc.sample_tick(1.0)
        health_mid = svc.health()
        if health_mid["ready"]:
            violations.append("health stayed ready at the refuse stage")
        if not any("memory pressure" in r for r in health_mid["reasons"]):
            violations.append(
                f"refuse-stage health reasons lack memory pressure: "
                f"{health_mid['reasons']}")

        # -- flooding publisher P2: 10x+ the drain rate by construction
        #    (saturated in-process bursts, no pacing). Refusals close its
        #    channel with 406; it reopens and retries until everything it
        #    ever got confirmed is accounted, nothing more.
        refusals_seen = 0

        async def p2_run() -> set[bytes]:
            nonlocal refusals_seen
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            conns.append(conn)
            ch = None
            sent: dict[int, int] = {}    # publish seq -> message index
            todo = list(range(p2_count))
            done: set[bytes] = set()
            deadline = asyncio.get_event_loop().time() + 60
            while todo or sent:
                if asyncio.get_event_loop().time() > deadline:
                    violations.append(
                        f"P2 never finished: {len(todo)} todo, "
                        f"{len(sent)} unresolved")
                    break
                if ch is None or ch.closed:
                    if ch is not None:
                        # a 406 refusal closed the channel: seqs no longer
                        # in `unconfirmed` were acked before the close and
                        # stay confirmed; the rest were never executed
                        refusals_seen += 1
                        pending = set(ch.unconfirmed)
                        for seq, idx in sent.items():
                            if seq in pending:
                                todo.append(idx)
                            else:
                                done.add(b"p2-%05d" % idx)
                        sent = {}
                        await asyncio.sleep(0.05)
                    ch = await conn.channel()
                    await ch.confirm_select()
                while todo and len(ch.unconfirmed) < 32:
                    idx = todo.pop()
                    seq = ch.basic_publish(b"p2-%05d" % idx + pad,
                                           routing_key=qn)
                    sent[seq] = idx
                try:
                    await ch.wait_unconfirmed_below(1, timeout=5)
                except Exception:
                    continue  # closed (refused) or still gated: resolve above
                done.update(b"p2-%05d" % idx for idx in sent.values())
                sent = {}
            return done

        p2_task = asyncio.create_task(p2_run())
        await wait_for(lambda: broker.metrics.flow_publishes_refused > 0,
                       10, "a refused publish at the refuse stage")

        # stage >= 1 tightened the resident cap: before the consumer can
        # drain the backlog away, the sweep must page bodies beyond
        # flow.page-resident out to the store
        await wait_for(lambda: broker.metrics.flow_paged_bodies > 0, 10,
                       "flow-paged bodies under pressure")

        # -- consumer attaches mid-refusal: draining must keep working
        #    while publishers are being refused
        c_conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(c_conn)
        c_ch = await c_conn.channel()
        await c_ch.basic_qos(prefetch_count=64)

        def on_msg(msg):
            body = bytes(msg.body[:8])
            deliveries[body] = deliveries.get(body, 0) + 1
            c_ch.basic_ack(msg.delivery_tag)

        await c_ch.basic_consume(qn, on_msg, consumer_tag="overload")
        await wait_for(
            lambda: sum(deliveries.values()) >= phase_a // 2, 15,
            "consumer drain progress during the refuse stage")
        drained_under_refuse = (flow.stage >= STAGE_REFUSE,
                                sum(deliveries.values()))
        if not drained_under_refuse[0]:
            violations.append(
                "pressure window ended before the drain-under-refuse "
                "observation (window too short for this host)")

        # -- the window closes: full recovery, publisher resume included
        await wait_for(lambda: flow.stage == 0, 30,
                       "recovery to stage 0 after the pressure window")
        confirmed |= await asyncio.wait_for(p2_task, 60)
        for _ in range(4):
            svc.sample_tick(1.0)
        health_end = svc.health()
        if not health_end["ready"]:
            violations.append(
                f"health not ready after recovery: {health_end['reasons']}")

        # the well-behaved publisher saw exactly stop -> resume and can
        # publish its remaining quota afterwards
        await wait_for(lambda: p1_ch.flow_events == [False, True], 10,
                       "channel.flow stop/resume pair on the idle publisher")
        if p1_ch.flow_events != [False, True]:
            violations.append(
                f"publisher flow events not [stop, resume]: "
                f"{p1_ch.flow_events}")
        for i in range(phase_resume):
            p1_ch.basic_publish(b"p1-%05d" % (phase_a + i) + pad,
                                routing_key=qn)
        await p1_ch.wait_unconfirmed_below(1, timeout=15)
        confirmed |= {b"p1-%05d" % (phase_a + i) for i in range(phase_resume)}

        # -- zero confirmed loss: every confirmed body delivered
        await wait_for(lambda: confirmed <= set(deliveries), 30,
                       "every confirmed message delivered")
        missing = sorted(confirmed - set(deliveries))
        if missing:
            violations.append(
                f"confirmed-but-lost: {len(missing)} messages "
                f"(first: {[m.decode() for m in missing[:5]]})")
        duplicates = sum(n - 1 for n in deliveries.values() if n > 1)

        # -- the hard invariants on the accountant itself
        if flow.peak_total > flow.hard_limit:
            violations.append(
                f"accounted peak {flow.peak_total} exceeded the hard "
                f"limit {flow.hard_limit}")
        await wait_for(lambda: flow.total <= flow.low_watermark, 10,
                       "accounted total back at/below the low watermark")
        if broker.metrics.flow_publishes_refused == 0:
            violations.append("no publish was ever refused")
        if refusals_seen == 0:
            violations.append("the flooder never observed a 406 refusal")

        # -- exact alert firings: memory-pressure and nothing else
        snapshot = svc.engine.snapshot()
        fired = tuple(snapshot["fired_rules"])
        if fired != OVERLOAD_ALERT_RULES:
            violations.append(
                f"alert firings not exact: expected {OVERLOAD_ALERT_RULES}, "
                f"got {fired}")
        if snapshot["firing"]:
            violations.append(
                f"alerts still firing after recovery: "
                f"{[i['rule'] for i in snapshot['firing']]}")

        # -- the event-bus/SLO demo assertions: the consumer saw the
        #    escalation, the alert and the burn; the budget drew down;
        #    the burn cleared once the post-recovery ticks went ready
        slo_snap = svc.slo.snapshot()
        slo_budget = slo_snap["slos"][0]["budget_remaining"]
        required_events = (
            "flow.stage.4",                       # ladder hit refuse
            "alert.fired.memory-pressure",
            "slo.burn-rate.readiness",
        )
        deadline = asyncio.get_event_loop().time() + 10
        while (not all(ev in observed_events for ev in required_events)
               and asyncio.get_event_loop().time() < deadline):
            await asyncio.sleep(0.05)
        event_stream_ok = True
        for ev in required_events:
            if ev not in observed_events:
                event_stream_ok = False
                violations.append(
                    f"event-bus consumer never saw {ev!r} "
                    f"(got {observed_events})")
        if slo_budget >= 1.0:
            violations.append(
                f"slo budget never drew down: {slo_budget}")
        if slo_snap["firing"]:
            violations.append(
                f"slo pairs still burning after recovery: "
                f"{[f['slo'] + ':' + f['pair'] for f in slo_snap['firing']]}")
        if slo_snap["fired_total"] < 2 or slo_snap["cleared_total"] \
                != slo_snap["fired_total"]:
            violations.append(
                f"slo burn/clear not exact: fired={slo_snap['fired_total']} "
                f"cleared={slo_snap['cleared_total']} (want both pairs "
                f"fired and cleared)")

        m = broker.metrics
        return {
            "seed": seed,
            "fingerprint": fingerprint,
            "messages": messages,
            "confirmed": len(confirmed),
            "delivered_unique": len(set(deliveries) & confirmed),
            "duplicates": duplicates,
            "drained_under_refuse": drained_under_refuse[1],
            "peak_accounted_bytes": flow.peak_total,
            "hard_limit": flow.hard_limit,
            "under_hard_limit": flow.peak_total <= flow.hard_limit,
            "refuse_stage_total_bytes": stage4_total,
            "final_stage": flow.stage,
            "final_total_bytes": flow.total,
            "low_watermark": flow.low_watermark,
            "publishes_refused": m.flow_publishes_refused,
            "refusal_channel_closes": refusals_seen,
            "paged_bodies": m.flow_paged_bodies,
            "paged_bytes": m.flow_paged_bytes,
            "flow_throttles": m.flow_throttles,
            "flow_resumes": m.flow_resumes,
            "escalations": m.flow_escalations,
            "deescalations": m.flow_deescalations,
            "chaos_pressure_ticks": m.chaos_pressure,
            "throttle_latency_s": round(
                throttle_t.get("end", 0.0) - throttle_t["start"], 3)
                if "start" in throttle_t and "end" in throttle_t else None,
            "hold_wait_ms": round(m.flow_hold_wait_ns / 1e6, 3),
            "hold_releases": m.flow_hold_releases,
            "health_mid": {"ready": health_mid["ready"],
                           "stage": health_mid["checks"]
                           ["memory_pressure"]["stage_label"]},
            "health_end": {"ready": health_end["ready"]},
            "alerts": {"fired_rules": list(fired),
                       "fired_total": snapshot["fired_total"],
                       "resolved_total": snapshot["resolved_total"]},
            "events": {"observed": observed_events,
                       "event_stream_ok": event_stream_ok,
                       "published": m.events_published_total,
                       "dropped": m.events_dropped_total},
            "slo": {"budget_remaining": slo_budget,
                    "fired_total": slo_snap["fired_total"],
                    "cleared_total": slo_snap["cleared_total"],
                    "slo_burned": slo_budget < 1.0},
            "chaos": runtime.status(),
            "violations": violations,
        }
    finally:
        from .. import events as events_mod
        events_mod.install(None)
        clear()
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        try:
            await srv.stop()
        except Exception:
            pass


# -- predictive-control spike soak -----------------------------------------

# seeded burst ramp: doubling bursts guarantee the two in-flight bursts
# after the reactive throttle crossing (the "frames already on the wire"
# lag) dwarf the refuse-enter gap, so the uncontrolled run always lands
# at the refuse stage while the pre-armed run stops two bursts earlier
# and peaks inside the throttle band — for every seed's +/-10% jitter.
_CTRL_BURSTS = 7
_CTRL_BURST_BASE = 12 * 1024
_CTRL_SPIKE_TICKS = 10
_CTRL_BURST_LAG = 2          # bursts that still land after a stop decision
_CTRL_BODY_PAD = 1024        # + 8-byte tag = 1032 accounted bytes/message
_CTRL_PRE = 32               # confirmed publishes before the spike
_CTRL_POST = 8               # confirmed publishes after recovery
_CTRL_PROBES = 3             # refusal-probe publishes at the peak
_CTRL_CREDIT = 16 * 1024     # publish credit the pre-arm must shrink/restore


def control_spike_sizes(seed: int) -> list[int]:
    """The seeded injection schedule: a doubling ramp with +/-10% jitter.
    Pure function of the seed — both on-runs replay it identically."""
    import random
    rng = random.Random(seed)
    sizes = []
    for i in range(_CTRL_BURSTS):
        sizes.append(int(_CTRL_BURST_BASE * (2 ** i) * rng.uniform(0.9, 1.1)))
    return sizes


async def _control_spike_run(seed: int, mode: str) -> dict:
    """One seeded spike episode. mode: "off" (no control plane), "on"
    (control applying decisions), "dry" (control logging but provably
    mutating nothing). Returns a report with per-run violations plus the
    raw decision-log bytes for cross-run comparison."""
    from ..amqp.properties import BasicProperties
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..control import ControlService
    from ..flow import STAGE_THROTTLE
    from ..store.memory import MemoryStore
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults

    broker = Broker(
        store=MemoryStore(),
        # no background sweeps: accounting moves only on the synchronous
        # publish/ack path, so the gate-total series (and therefore the
        # decision log) is a pure function of the seed
        message_sweep_interval_s=3600.0,
        # keep every body resident (no passivation, pager opted out): the
        # spike must confront the admission ladder head-on, not drain
        # into the store through the stage-1 pager mid-ramp
        queue_max_resident=1_000_000,
        flow_page_resident=0,
        flow_high_watermark=256 * 1024,
        flow_refuse_watermark=700 * 1024,
        flow_hard_limit=4 * 1024 * 1024,
        flow_publish_credit=_CTRL_CREDIT,
        flow_consumer_buffer=4 * 1024 * 1024,
    )
    flow = broker.flow
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                       heartbeat_s=0)
    # harness-ticked telemetry (the control plane reads its ring); every
    # alert threshold is unreachable so firings can't vary the run
    broker.telemetry = TelemetryService(
        broker, interval_s=1.0, ring_ticks=64,
        rules=alert_defaults(backlog_growth=1e12, stall_ticks=10**6,
                             repl_lag=1e12, loop_lag_ms=1e12,
                             memory_stage=1e12))
    svc = broker.telemetry

    control = None
    if mode != "off":
        control = ControlService(
            broker, interval_s=1.0, dry_run=(mode == "dry"),
            admission=True, rebalance=False, prefetch=False,
            horizon_s=12.0, arm_ticks=2, cooldown_s=6.0,
            credit_factor=0.5, credit_min=4096, log_size=512)

    max_stage = {"v": 0}
    flow.listeners.append(
        lambda old, new: max_stage.__setitem__("v", max(max_stage["v"], new)))

    violations: list[str] = []
    conns: list = []
    qn = "ctrl_q"
    pad = b"x" * _CTRL_BODY_PAD
    msg_bytes = _CTRL_BODY_PAD + 8
    props = BasicProperties()
    deliveries: dict[bytes, int] = {}
    floor_max = 0

    async def wait_for(predicate, timeout: float, what: str) -> bool:
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate():
            if asyncio.get_event_loop().time() > deadline:
                violations.append(f"[{mode}] timeout waiting for {what}")
                return False
            await asyncio.sleep(0.01)
        return True

    try:
        await srv.start()

        # -- pre-phase: a confirmed baseline backlog (the zero-loss set)
        p1 = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(p1)
        p1_ch = await p1.channel()
        await p1_ch.confirm_select()
        await p1_ch.queue_declare(qn)
        for i in range(_CTRL_PRE):
            p1_ch.basic_publish(b"p1-%05d" % i + pad, routing_key=qn)
        await p1_ch.wait_unconfirmed_below(1, timeout=15)
        confirmed: set[bytes] = {b"p1-%05d" % i for i in range(_CTRL_PRE)}

        # -- spike: seeded doubling bursts, injected synchronously so the
        # accountant sees the exact same byte series every run. The
        # injector stops once it observes stage >= THROTTLE at a tick
        # start, but the next _CTRL_BURST_LAG bursts still land — the
        # in-flight frames a real publisher has already sent. The earlier
        # the ladder throttles, the lower the peak: that delta is what
        # separates the pre-armed run from the reactive one.
        sizes = control_spike_sizes(seed)
        injected = 0
        stop_tick = None
        for t in range(_CTRL_SPIKE_TICKS):
            if stop_tick is None and flow.stage >= STAGE_THROTTLE:
                stop_tick = t
            if t < len(sizes) and (stop_tick is None
                                   or t < stop_tick + _CTRL_BURST_LAG):
                for _ in range(max(1, sizes[t] // msg_bytes)):
                    routed, _ = broker.publish_sync(
                        "/", "", qn, props, b"inj-%04d" % injected + pad)
                    if not routed:
                        violations.append(f"[{mode}] injected publish "
                                          f"{injected} not routed")
                    injected += 1
            svc.sample_tick(1.0)
            if control is not None:
                await control.step(1.0)
                floor_max = max(floor_max, flow.floor)
            await asyncio.sleep(0.01)
        spike_peak = flow.peak_total

        # -- refusal probe at the peak: an uncontrolled run sits at the
        # refuse stage (406 channel close); a pre-armed run sits at the
        # throttle floor and accepts the probe under the shrunk credit
        pb = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(pb)
        pb_ch = await pb.channel()
        for i in range(_CTRL_PROBES):
            try:
                pb_ch.basic_publish(b"pb-%05d" % i + pad, routing_key=qn)
            except Exception:
                break  # channel already closed by a 406
        if mode == "on":
            await asyncio.sleep(0.3)
            if broker.metrics.flow_publishes_refused:
                violations.append(
                    f"[{mode}] pre-armed run refused "
                    f"{broker.metrics.flow_publishes_refused} publishes")
        else:
            await wait_for(
                lambda: broker.metrics.flow_publishes_refused > 0, 10,
                "a refused publish at the uncontrolled peak")

        # -- drain: consumer attaches, backlog empties to a quiescent gate
        c_conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(c_conn)
        c_ch = await c_conn.channel()
        await c_ch.basic_qos(prefetch_count=64)

        def on_msg(msg):
            deliveries[bytes(msg.body[:8])] = \
                deliveries.get(bytes(msg.body[:8]), 0) + 1
            c_ch.basic_ack(msg.delivery_tag)

        await c_ch.basic_consume(qn, on_msg, consumer_tag="ctrl")
        await wait_for(lambda: flow.components.get("bodies", 0) == 0, 30,
                       "full backlog drain")

        # -- recovery: at the quiescent barrier (gate total is exactly 0,
        # so the relax inputs are identical every run) tick the control
        # plane until the engine disarms — the relax decision
        if control is not None:
            for _ in range(10):
                if not control.engine.snapshot()["armed"]:
                    break
                await control.step(1.0)
                floor_max = max(floor_max, flow.floor)
            if control.engine.snapshot()["armed"]:
                violations.append(f"[{mode}] engine never disarmed at the "
                                  f"quiescent barrier")
        await wait_for(lambda: flow.stage == 0, 15,
                       "stage-0 recovery after the drain")
        await wait_for(lambda: p1_ch.flow_events == [False, True], 10,
                       "channel.flow stop/resume pair on the publisher")

        # -- post-phase: confirms flow again after the episode
        for i in range(_CTRL_POST):
            p1_ch.basic_publish(b"p1-%05d" % (_CTRL_PRE + i) + pad,
                                routing_key=qn)
        await p1_ch.wait_unconfirmed_below(1, timeout=15)
        confirmed |= {b"p1-%05d" % (_CTRL_PRE + i)
                      for i in range(_CTRL_POST)}
        await wait_for(lambda: confirmed <= set(deliveries), 30,
                       "every confirmed message delivered")
        missing = sorted(confirmed - set(deliveries))
        if missing:
            violations.append(
                f"[{mode}] confirmed-but-lost: {len(missing)} messages "
                f"(first: {[m.decode() for m in missing[:5]]})")
        if flow.peak_total > flow.hard_limit:
            violations.append(
                f"[{mode}] accounted peak {flow.peak_total} exceeded the "
                f"hard limit {flow.hard_limit}")

        m = broker.metrics
        return {
            "mode": mode,
            "seed": seed,
            "injected": injected,
            "max_stage": max_stage["v"],
            "spike_peak_bytes": spike_peak,
            "peak_bytes": flow.peak_total,
            "publishes_refused": m.flow_publishes_refused,
            "decisions": m.control_decisions,
            "applied": m.control_applied,
            "suppressed": m.control_suppressed,
            "dry_runs": m.control_dry_run,
            "control_errors": m.control_errors,
            "floor_max": floor_max,
            "floor_end": flow.floor,
            "credit_end": broker.flow_publish_credit,
            "confirmed": len(confirmed),
            "delivered_unique": len(set(deliveries) & confirmed),
            "log_bytes": (control.decision_log_bytes()
                          if control is not None else b""),
            "violations": violations,
        }
    finally:
        if control is not None:
            try:
                await control.stop()
            except Exception:
                pass
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        try:
            await srv.stop()
        except Exception:
            pass


async def run_control_soak(seed: int) -> dict:
    """Predictive-control spike soak: the same seeded byte-for-byte burst
    ramp is replayed four times — uncontrolled, controlled, controlled
    again (same seed), and dry-run — and the runs are compared. The
    report's ``violations`` list is empty iff:

    1. **The pre-armed run beats the reactive ladder** — strictly lower
       maximum flow stage and strictly fewer refused publishes than the
       uncontrolled run (which must actually reach the refuse stage, or
       the spike proved nothing).
    2. **Zero confirmed-message loss in every run.**
    3. **The decision log is deterministic** — the two same-seed
       controlled runs serialize byte-identically, and non-trivially
       (at least pre-arm + relax).
    4. **Dry-run mutates nothing** — decisions are logged and counted,
       but the stage floor never moves, the publish credit is untouched,
       nothing is applied, and the broker behaves exactly like the
       uncontrolled run (same max stage, refusals still happen).
    """
    import hashlib

    off = await _control_spike_run(seed, "off")
    on = await _control_spike_run(seed, "on")
    on2 = await _control_spike_run(seed, "on")
    dry = await _control_spike_run(seed, "dry")

    violations: list[str] = []
    for run in (off, on, on2, dry):
        violations.extend(run.pop("violations"))

    from ..flow import STAGE_REFUSE, STAGE_THROTTLE
    if off["publishes_refused"] == 0 or off["max_stage"] < STAGE_REFUSE:
        violations.append(
            f"uncontrolled run never hit the refuse stage "
            f"(max_stage={off['max_stage']}, "
            f"refused={off['publishes_refused']})")
    for run in (on, on2):
        if run["max_stage"] >= off["max_stage"]:
            violations.append(
                f"pre-armed max stage {run['max_stage']} not strictly "
                f"below uncontrolled {off['max_stage']}")
        if run["publishes_refused"] >= max(1, off["publishes_refused"]):
            violations.append(
                f"pre-armed run refused {run['publishes_refused']} "
                f"publishes (uncontrolled: {off['publishes_refused']})")
        if run["max_stage"] > STAGE_THROTTLE:
            violations.append(
                f"pre-armed run escalated past the throttle floor "
                f"(max_stage={run['max_stage']})")
        if run["applied"] < 2:
            violations.append(
                f"controlled run applied only {run['applied']} decisions "
                f"(expected pre-arm + relax)")
        if run["floor_end"] != 0 or run["credit_end"] != _CTRL_CREDIT:
            violations.append(
                f"relax did not restore state: floor={run['floor_end']} "
                f"credit={run['credit_end']}")
    if not on["log_bytes"]:
        violations.append("controlled run produced an empty decision log")
    if on["log_bytes"] != on2["log_bytes"]:
        violations.append(
            "same-seed decision logs differ between controlled runs")
    if dry["decisions"] < 1 or dry["dry_runs"] < 1:
        violations.append("dry-run logged no decisions")
    if dry["applied"] != 0:
        violations.append(
            f"dry-run applied {dry['applied']} decisions")
    if dry["floor_max"] != 0:
        violations.append(
            f"dry-run moved the stage floor (floor_max={dry['floor_max']})")
    if dry["credit_end"] != _CTRL_CREDIT:
        violations.append(
            f"dry-run changed the publish credit ({dry['credit_end']})")
    if dry["max_stage"] != off["max_stage"] or dry["publishes_refused"] == 0:
        violations.append(
            f"dry-run behavior diverged from uncontrolled "
            f"(max_stage={dry['max_stage']} vs {off['max_stage']}, "
            f"refused={dry['publishes_refused']})")

    def digest(run: dict) -> None:
        raw = run.pop("log_bytes")
        run["log_sha256"] = hashlib.sha256(raw).hexdigest()
        run["log_len"] = len(raw)

    for run in (off, on, on2, dry):
        digest(run)
    return {
        "seed": seed,
        "sizes": control_spike_sizes(seed),
        "off": off,
        "on": on,
        "on_repeat": on2,
        "dry": dry,
        "violations": violations,
    }


async def run_connection_churn(cycles: int = 500, *,
                               bodies_per_cycle: int = 3,
                               body_bytes: int = 2048) -> dict:
    """Connection-churn leak check: `cycles` connect / declare-exclusive /
    publish-confirmed / disconnect rounds (every other one an abrupt
    socket abort instead of a clean Connection.Close), then assert the
    memory accountant is back to zero — the exclusive queues die with
    their connections, so any surviving accounted byte is a leak in the
    hold/release or queue-teardown accounting."""
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..store.memory import MemoryStore

    broker = Broker(store=MemoryStore(), queue_max_resident=64,
                    message_sweep_interval_s=0.05,
                    flow_high_watermark=64 * 1024)
    flow = broker.flow
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                       heartbeat_s=0)
    violations: list[str] = []
    body = b"c" * body_bytes
    aborted = 0
    try:
        await srv.start()
        for i in range(cycles):
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            try:
                ch = await conn.channel()
                await ch.confirm_select()
                qn = f"churn_{i}"
                await ch.queue_declare(qn, exclusive=True)
                for _ in range(bodies_per_cycle):
                    ch.basic_publish(body, routing_key=qn)
                await ch.wait_unconfirmed_below(1, timeout=10)
                if i % 2:
                    # abrupt death: no Connection.Close — teardown
                    # accounting must still release everything
                    try:
                        conn.reader._transport.abort()
                        aborted += 1
                    except Exception:
                        await conn.close()
                else:
                    await conn.close()
            except Exception as exc:
                violations.append(f"cycle {i}: {type(exc).__name__}: {exc}")
                try:
                    await conn.close()
                except Exception:
                    pass
                break

        deadline = asyncio.get_event_loop().time() + 15
        while broker.connections and \
                asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.02)
        if broker.connections:
            violations.append(
                f"{len(broker.connections)} connection(s) never torn down")
        # a couple of sweep ticks so the polled components resample
        await asyncio.sleep(0.15)

        leaked = broker.resident_bytes + broker.held_bytes
        if leaked:
            violations.append(
                f"accounted-bytes leak after churn: resident="
                f"{broker.resident_bytes} held={broker.held_bytes}")
        live_queues = sum(len(v.queues) for v in broker.vhosts.values())
        if live_queues:
            violations.append(
                f"{live_queues} exclusive queue(s) survived their "
                f"connections")
        gate_components = {
            k: v for k, v in flow.components.items()
            if k in ("bodies", "held") and v}
        if gate_components:
            violations.append(
                f"flow accountant still charged after churn: "
                f"{gate_components}")
        return {
            "cycles": cycles,
            "aborted": aborted,
            "bodies_per_cycle": bodies_per_cycle,
            "body_bytes": body_bytes,
            "leaked_bytes": leaked,
            "final_total_bytes": flow.total,
            "peak_accounted_bytes": flow.peak_total,
            "final_stage": flow.stage,
            "live_queues": sum(len(v.queues) for v in broker.vhosts.values()),
            "violations": violations,
        }
    finally:
        try:
            await srv.stop()
        except Exception:
            pass


async def _alert_phase(srv, cl, violations: list[str]) -> dict:
    """Invariant 6b: drive the surviving node's telemetry through a
    scripted backlog (publish with no consumer -> backlog-growth) and a
    stalled consumer (prefetch 1, never acks -> consumer-stall), ticking
    the sampler by hand. The engine's input is then a pure function of
    the workload, so the set of fired rules must match
    EXPECTED_ALERT_RULES exactly — no more, no fewer.

    Invariant 6c (event bus): a plain AMQP consumer bound ``alert.#`` +
    ``lifecycle.#`` on ``amq.chanamq.event`` must receive exactly the
    engine's fire/resolve transitions as messages — same rules, same
    order — and zero lifecycle events (nothing drains in this soak).
    Deterministic mod the wall-clock ``ts`` stamp in each body."""
    import json as json_mod

    from .. import events as events_mod
    from ..client.client import AMQPClient

    svc = srv.broker.telemetry
    aq = next(f"ca{i}" for i in range(200)
              if cl.queue_owner("/", f"ca{i}") == cl.name)
    eq = next(f"ce{i}" for i in range(200)
              if cl.queue_owner("/", f"ce{i}") == cl.name)
    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    bus_events: list[dict] = []
    try:
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare(aq)

        # event consumer FIRST, bus installed after its own connection
        # setup so the collected stream starts exactly at the phase start
        e_ch = await conn.channel()
        await e_ch.queue_declare(eq)
        await e_ch.queue_bind(eq, "amq.chanamq.event", "alert.#")
        await e_ch.queue_bind(eq, "amq.chanamq.event", "lifecycle.#")

        def on_event(msg):
            bus_events.append(json_mod.loads(bytes(msg.body)))
            e_ch.basic_ack(msg.delivery_tag)

        await e_ch.basic_consume(eq, on_event, consumer_tag="soak-events")
        events_mod.install(events_mod.EventBus(srv.broker))

        # baseline tick: the queue's ring slot needs one pre-backlog
        # sample for the growth window to measure against
        svc.sample_tick(1.0)
        for i in range(120):
            ch.basic_publish(f"a{i:04d}".encode(), routing_key=aq)
        await ch.wait_unconfirmed_below(1, timeout=15)
        # two post-backlog ticks: +120 depth inside the 5-tick window on
        # both -> breach streak reaches for_ticks=2 -> backlog-growth fires
        svc.sample_tick(1.0)
        svc.sample_tick(1.0)

        # stalled consumer: prefetch 1, never acks. The first delivery
        # lands before the next tick (deliver_rate blips once), then the
        # queue has depth > 0, consumers > 0 and zero deliver rate for
        # stall_ticks=3 straight ticks -> consumer-stall fires
        first = asyncio.Event()
        await ch.basic_qos(prefetch_count=1)
        await ch.basic_consume(aq, lambda msg: first.set(),
                               consumer_tag="stalled")
        await asyncio.wait_for(first.wait(), 10)
        for _ in range(4):
            svc.sample_tick(1.0)

        snapshot = svc.engine.snapshot()
        fired = tuple(snapshot["fired_rules"])
        if fired != EXPECTED_ALERT_RULES:
            violations.append(
                f"alert firings not exact: expected {EXPECTED_ALERT_RULES}, "
                f"got {fired}")

        # invariant 6c: the consumed event stream mirrors the engine's own
        # transition history exactly (order and rules), with no lifecycle
        # noise. Emits are synchronous at the tick; only the AMQP delivery
        # to our consumer is async, so give it a bounded settle window.
        expected_stream = [
            ("fired" if ev["event"] == "fired" else "cleared", ev["rule"])
            for ev in svc.engine.history]
        deadline = asyncio.get_event_loop().time() + 10
        while (len(bus_events) < len(expected_stream)
               and asyncio.get_event_loop().time() < deadline):
            await asyncio.sleep(0.05)
        got_stream = [tuple(ev["event"].split(".", 1)[-1].split(".", 1))
                      if ev["event"].startswith("alert.")
                      else ("lifecycle", ev["event"])
                      for ev in bus_events]
        lifecycle_seen = [ev["event"] for ev in bus_events
                          if ev["event"].startswith("lifecycle.")]
        if lifecycle_seen:
            violations.append(
                f"unexpected lifecycle events on the bus: {lifecycle_seen}")
        if got_stream != expected_stream:
            violations.append(
                f"event-bus alert stream mismatch: expected "
                f"{expected_stream}, got {got_stream}")
        return {
            "queue": aq,
            "fired_rules": list(fired),
            "fired_total": snapshot["fired_total"],
            "resolved_total": snapshot["resolved_total"],
            "firing_now": [
                f"{i['rule']}:{i['entity']}" for i in snapshot["firing"]],
            "bus_events": [ev["event"] for ev in bus_events],
            "bus_stream_exact": got_stream == expected_stream,
        }
    finally:
        events_mod.install(None)
        try:
            await conn.close()
        except Exception:
            pass


async def _elastic_run(seed: int) -> dict:
    """One elasticity episode: 3-node cluster + joiner, join-triggered
    rebalance, graceful drain, kill -9 mid-drain, and a fenced stale
    owner — all on PRIVATE per-node stores. Returns a report plus the
    normalized decision/evacuation log bytes for same-seed comparison."""
    import hashlib

    from ..amqp.properties import BasicProperties
    from ..client.client import AMQPClient
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..cluster.membership import LEFT
    from ..cluster.node import ClusterNode
    from ..control import ControlService
    from ..store.memory import MemoryStore
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults

    # node names are host:port and feed the hash ring, so every placement
    # choice (follower sets, evacuation targets, promotion winners) is a
    # function of the ports. Ephemeral ports would make same-seed runs
    # diverge; fixed seed-derived ports (below the 32768+ ephemeral range)
    # make the whole episode replayable byte-for-byte. Only the cluster
    # RPC port matters — the AMQP listener stays ephemeral.
    cluster_base = 23000 + (seed % 512) * 8

    async def start_node(seeds, port):
        # flow ladder present (the control plane projects against it) but
        # with watermarks far above the workload: stage stays 0 throughout
        broker = Broker(store=MemoryStore(),
                        flow_high_watermark=1 << 40,
                        flow_hard_limit=1 << 42)
        srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                           heartbeat_s=0)
        await srv.start()
        cl = ClusterNode(srv.broker, "127.0.0.1", port, seeds,
                         heartbeat_interval_s=0.2, failure_timeout_s=1.5,
                         replicate_factor=2, replicate_sync=True,
                         replicate_ack_timeout_ms=2000,
                         drain_budget_s=20.0)
        await cl.start()
        return srv, cl

    async def until(predicate, timeout, what):
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate():
            if asyncio.get_event_loop().time() > deadline:
                violations.append(f"timeout waiting for {what}")
                return False
            await asyncio.sleep(0.05)
        return True

    persistent = BasicProperties(delivery_mode=2)
    violations: list[str] = []
    conns: list = []
    a_srv = a_cl = b_srv = b_cl = c_srv = c_cl = d_srv = d_cl = None
    control = None
    try:
        a_srv, a_cl = await start_node([], cluster_base)
        b_srv, b_cl = await start_node([a_cl.name], cluster_base + 1)
        c_srv, c_cl = await start_node([a_cl.name], cluster_base + 2)
        await until(
            lambda: all(len(cl.membership.alive_members()) == 3
                        for cl in (a_cl, b_cl, c_cl)),
            10, "3-node membership")

        # -- queue placement, pinned by role so same-seed runs make the
        #    same logical decisions despite ephemeral node names
        def placed(ring, prefix, *roles):
            want = [cl.name for cl in roles]
            return next(
                f"{prefix}{i}" for i in range(4000)
                if ring.preference_entity(
                    "q", "/", f"{prefix}{i}", len(want))[:len(want)] == want)

        eq = [placed(a_cl.ring, f"eq{j}x", a_cl, b_cl) for j in range(3)]
        cq = [placed(a_cl.ring, f"cq{j}x", c_cl, b_cl) for j in range(2)]

        # -- control plane on A, harness-stepped (no timers): tick 1 now
        #    so the join observed later counts as elasticity, not boot.
        #    The eq queues are declared BEFORE the first sample so tick 2
        #    sees real publish-rate deltas (a queue's first sample
        #    baselines its counters at zero rate)
        a_srv.broker.telemetry = TelemetryService(
            a_srv.broker, interval_s=1.0, ring_ticks=64,
            rules=alert_defaults(
                backlog_growth=1e12, stall_ticks=10**6, repl_lag=1e12,
                loop_lag_ms=1e12, memory_stage=1e12))
        control = ControlService(
            a_srv.broker, interval_s=1.0, dry_run=False,
            admission=False, rebalance=True, prefetch=False)
        decl = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        conns.append(decl)
        decl_ch = await decl.channel()
        for qname in eq:
            await decl_ch.queue_declare(qname, durable=True)
        await decl.close()
        a_srv.broker.telemetry.sample_tick(1.0)
        await control.step(1.0)

        # -- confirmed backlog (the zero-loss set); body length is fixed
        #    so byte-counters (and the load EWMA in the decision log) are
        #    a pure function of message COUNTS, not of searched names
        confirmed: dict[str, set] = {}
        mseq = 0

        async def fill(srv, qname, count):
            nonlocal mseq
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            conns.append(conn)
            ch = await conn.channel()
            await ch.confirm_select()
            await ch.queue_declare(qname, durable=True)
            bodies = set()
            for _ in range(count):
                body = b"m%06d" % mseq
                mseq += 1
                ch.basic_publish(body, routing_key=qname,
                                 properties=persistent)
                bodies.add(body.decode())
            await ch.wait_unconfirmed_below(1, timeout=20)
            confirmed[qname] = bodies
            await conn.close()

        # distinct per-queue rates make the engine's busiest-queue pick
        # unambiguous: eq[0] is always the join-seeding move
        await fill(a_srv, eq[0], 30)
        await fill(a_srv, eq[1], 20)
        await fill(a_srv, eq[2], 10)
        await fill(c_srv, cq[0], 12)
        await fill(c_srv, cq[1], 12)

        # -- crash plan: drain.tick fires once per evacuation attempt;
        #    A's drain burns invocations 1-2 (eq[1], eq[2] — eq[0] will
        #    have moved to the joiner), C's drain hits 3 (cq[0]) and the
        #    crash lands on 4: C dies holding cq[1], half-drained
        plan = FaultPlan(seed, [
            FaultRule(name="kill-during-drain", kind="crash",
                      sites=["drain.tick"], after=3, count=1,
                      nodes=["victim"]),
        ])
        runtime = install(plan, metrics=b_srv.broker.metrics)
        fingerprint = plan.fingerprint()
        crashed = asyncio.Event()

        def crash_victim() -> None:
            crashed.set()
            task = c_cl.lifecycle._task
            if task is not None:
                task.cancel()  # deterministic: cq[1] never hands off

            async def _die():
                for part in (c_cl, c_srv):
                    try:
                        await part.stop()
                    except Exception:
                        pass
            asyncio.get_event_loop().create_task(_die())

        runtime.on_crash("victim", crash_victim)

        # -- phase: join. D comes up; the control plane seeds it with the
        #    busiest movable queue through the normal holdership machinery
        d_srv, d_cl = await start_node([a_cl.name], cluster_base + 3)
        await until(
            lambda: all(len(cl.membership.alive_members()) == 4
                        for cl in (a_cl, b_cl, c_cl, d_cl)),
            10, "4-node membership")
        a_srv.broker.telemetry.sample_tick(1.0)
        control.note_member_join(d_cl.name)
        decisions = await control.step(1.0)
        join_moves = [d for d in decisions
                      if d["kind"] == "rebalance.move"
                      and d["action"].get("join")]
        if len(join_moves) != 1:
            violations.append(
                f"expected exactly 1 join-rebalance decision, "
                f"saw {len(join_moves)}")
        elif join_moves[0]["action"]["name"] != eq[0] \
                or join_moves[0]["action"]["target"] != d_cl.name:
            violations.append(
                f"join move picked {join_moves[0]['action']} "
                f"(wanted busiest {eq[0]} -> joiner)")
        await until(
            lambda: d_cl.queue_metas.get(("/", eq[0]), {}).get("holder")
            == d_cl.name and eq[0] in d_srv.broker.vhosts["/"].queues,
            10, "join move to materialize on the joiner")

        # fencing-phase queue: owned by B with its replica on the joiner,
        # declared on the 4-node ring so the follower really is D
        fq = placed(b_cl.ring, "fqx", b_cl, d_cl)
        await fill(b_srv, fq, 8)

        # -- phase: graceful drain of A (zero-loss evacuation, then LEFT)
        a_cl.lifecycle.drain()
        a_report = await a_cl.lifecycle.wait(30)
        if a_report["state"] != "drained" or a_report["queues_moved"] != 2 \
                or a_report["failed"] or a_report["pinned"]:
            violations.append(f"drain of A did not complete: {a_report}")
        await until(
            lambda: b_cl.membership.lifecycle_of(a_cl.name) == LEFT
            and d_cl.membership.lifecycle_of(a_cl.name) == LEFT,
            10, "A's `left` state to gossip")
        if a_cl.name in b_cl.membership.placement_members():
            violations.append("left node still placement-eligible on B")

        # -- phase: kill -9 mid-drain. C evacuates cq[0], dies before
        #    cq[1]; B (the replica) must promote the remainder
        promotions_before = (a_srv.broker.metrics.repl_promotions
                            + b_srv.broker.metrics.repl_promotions
                            + c_srv.broker.metrics.repl_promotions
                            + d_srv.broker.metrics.repl_promotions)
        c_cl.lifecycle.drain()
        try:
            await c_cl.lifecycle.wait(20)
        except (asyncio.CancelledError, asyncio.TimeoutError):
            pass
        if not crashed.is_set():
            violations.append("kill-during-drain rule never fired")

        # C's drain hands cq[0] to its best-synced replica — after the
        # join reshuffle that can be B (the original follower) or D (the
        # re-picked one); either way it must land on exactly one live node
        def _cq0_landed() -> bool:
            holder = b_cl.queue_metas.get(("/", cq[0]), {}).get("holder")
            if holder == b_cl.name:
                return cq[0] in b_srv.broker.vhosts["/"].queues
            if holder == d_cl.name:
                return cq[0] in d_srv.broker.vhosts["/"].queues
            return False

        await until(_cq0_landed, 10,
                    "evacuated cq[0] to land on a live node (B or D)")
        # the unmoved remainder cq[1] must be promoted by whichever node
        # held its replica when C died (B originally; D after the join
        # reshuffle re-picked followers)
        def _cq1_promoted() -> bool:
            holder = b_cl.queue_metas.get(("/", cq[1]), {}).get("holder")
            if holder == b_cl.name:
                return cq[1] in b_srv.broker.vhosts["/"].queues
            if holder == d_cl.name:
                return cq[1] in d_srv.broker.vhosts["/"].queues
            return False

        await until(_cq1_promoted, 10,
                    "a survivor to promote the unmoved remainder cq[1]")
        failovers = (a_srv.broker.metrics.repl_promotions
                     + b_srv.broker.metrics.repl_promotions
                     + c_srv.broker.metrics.repl_promotions
                     + d_srv.broker.metrics.repl_promotions
                     - promotions_before)
        if failovers != 1:
            violations.append(
                f"expected exactly 1 failover promotion from the "
                f"mid-drain crash, saw {failovers}")

        # -- phase: partition heals into a fenced stale owner. B is
        #    isolated control-plane-wise (heartbeats cancelled, inbound
        #    pings and meta broadcasts fail) while its data plane still
        #    reaches D; D promotes fq and bumps its epoch; B — still
        #    thinking it owns — ships the stale epoch and must be refused.
        #    First let every live follower ack B's log heads: any copy D
        #    promotes during the partition is then content-complete. Acks
        #    piggyback on ships, and a wholesale resync finishes silently
        #    — probe the follower's applied seq like prepare_handoff does
        async def _b_heads_synced() -> bool:
            repl_mgr = b_cl.replication
            for (vhost, name), r in list(repl_mgr._logs.items()):
                for follower, acked in list(r.followers.items()):
                    if not b_cl.membership.is_alive(follower):
                        continue
                    if acked >= r.seq:
                        continue
                    try:
                        reply = await repl_mgr.client_for(follower).call(
                            "repl.probe",
                            {"vhost": vhost, "queue": name,
                             "owner": b_cl.name},
                            timeout_s=1.0)
                        applied = int(reply.get("applied", 0))
                        if applied > acked:
                            r.followers[follower] = applied
                    except Exception:
                        return False
                if r.live_ack_floor() < r.seq:
                    return False
            return True

        sync_deadline = asyncio.get_event_loop().time() + 10
        while not await _b_heads_synced():
            if asyncio.get_event_loop().time() > sync_deadline:
                violations.append(
                    "timeout waiting for B's followers to sync to head "
                    "before the partition")
                break
            await asyncio.sleep(0.05)
        b_mem = b_cl.membership
        if b_mem._task is not None:
            b_mem._task.cancel()
            b_mem._task = None
        # freeze B's anti-entropy too: a pull from D mid-partition would
        # hand it the promoted holdership through the side door and it
        # would stand down before ever shipping a stale epoch
        if b_cl._anti_entropy_task is not None:
            b_cl._anti_entropy_task.cancel()
            b_cl._anti_entropy_task = None

        async def _refuse_rpc(payload):
            raise OSError("isolated for the fencing phase")

        b_cl.rpc.register("cluster.ping", _refuse_rpc)
        b_cl.rpc.register("meta.apply", _refuse_rpc)
        await until(
            lambda: d_cl.queue_metas.get(("/", fq), {}).get("holder")
            == d_cl.name and fq in d_srv.broker.vhosts["/"].queues,
            15, "D to promote fq after B is isolated")
        stale_conn = await AMQPClient.connect("127.0.0.1",
                                              b_srv.bound_port)
        conns.append(stale_conn)
        stale_ch = await stale_conn.channel()
        await stale_ch.confirm_select()
        for i in range(3):
            # stale-owner publishes: B appends locally and ships with its
            # old epoch; confirms must NOT come back (D refuses the ship)
            try:
                await stale_ch.basic_publish_confirmed(
                    b"stale%02d" % i, routing_key=fq,
                    properties=persistent, timeout=1.5)
                violations.append(
                    f"stale owner B got publish {i} confirmed while "
                    f"fenced off")
            except Exception:
                pass
        refusals = d_srv.broker.metrics.lifecycle_stale_epoch_refused
        if refusals < 1:
            violations.append(
                "no stale-epoch ship was refused during the partition")
        # heal: B rejoins, learns the higher-epoch holdership via
        # anti-entropy, and stands down
        b_cl.rpc.register("cluster.ping", b_mem._on_ping)
        b_cl.rpc.register("meta.apply", b_cl._h_meta_apply)
        b_mem._task = asyncio.get_event_loop().create_task(
            b_mem._heartbeat_loop())
        b_cl._anti_entropy_task = asyncio.get_event_loop().create_task(
            b_cl._anti_entropy_loop())
        await until(
            lambda: b_cl.membership.is_alive(d_cl.name)
            and d_cl.membership.is_alive(b_cl.name),
            10, "partition to heal")
        await until(
            lambda: b_cl.queue_metas.get(("/", fq), {}).get("holder")
            == d_cl.name, 10, "healed B to adopt D's fenced holdership")

        # -- quiesce: exactly one live holder per queue, cluster-wide.
        #    Promotions taken while B was dark resolve through the epoch
        #    merge (B stands down on every queue D out-claimed), so give
        #    anti-entropy a bounded window to converge before asserting
        live = [(a_srv, a_cl), (b_srv, b_cl), (d_srv, d_cl)]

        def claimants(qname):
            claims = []
            for srv, cl in live:
                meta = cl.queue_metas.get(("/", qname), {})
                vhost = srv.broker.vhosts.get("/")
                queue = vhost.queues.get(qname) if vhost else None
                if meta.get("holder") == cl.name and queue is not None \
                        and not queue.deleted:
                    claims.append((srv, cl))
            return claims

        everything = eq + cq + [fq]
        await until(
            lambda: all(len(claimants(q)) == 1 for q in everything),
            15, "exactly one live holder per queue at quiesce")
        owners: dict[str, tuple] = {}
        for qname in everything:
            claims = claimants(qname)
            if len(claims) != 1:
                violations.append(
                    f"queue {qname}: {len(claims)} live holders at "
                    f"quiesce (want exactly 1)")
            if claims:
                owners[qname] = claims[0]

        # -- zero confirmed loss: every confirmed body is consumable from
        #    the queue's current holder
        lost = 0
        for qname, bodies in confirmed.items():
            holder = owners.get(qname)
            if holder is None:
                lost += len(bodies)
                continue
            srv, _cl = holder
            got: set = set()
            done = asyncio.Event()
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            conns.append(conn)
            ch = await conn.channel()
            await ch.basic_qos(prefetch_count=256)

            def on_msg(msg, got=got, want=bodies, done=done, ch=ch):
                got.add(bytes(msg.body).decode())
                ch.basic_ack(msg.delivery_tag)
                if want <= got:
                    done.set()

            await ch.basic_consume(qname, on_msg,
                                   consumer_tag="elastic-verify")
            try:
                await asyncio.wait_for(done.wait(), 10)
            except asyncio.TimeoutError:
                pass
            missing = bodies - got
            if missing:
                lost += len(missing)
                violations.append(
                    f"queue {qname}: {len(missing)} confirmed messages "
                    f"lost (first: {sorted(missing)[:3]})")
            await conn.close()

        # -- stream cursors survive the churn: a stream on B (never
        #    drained, crash-promoted, isolated AND healed) must still
        #    resume contiguously at committed+1
        sq = next(f"esx{i}" for i in range(4000)
                  if b_cl.ring.owner_entity("q", "/", f"esx{i}")
                  == b_cl.name)
        stream = await _stream_cursor_check(b_srv, sq, 30, violations)

        # -- normalized decision/evacuation log: two same-seed runs must
        #    serialize byte-identically once node names and searched queue
        #    names are replaced by their logical roles
        raw = (control.decision_log_bytes() + b"\n"
               + a_cl.lifecycle.evacuation_log_bytes())
        text = raw.decode()
        aliases = [(a_cl.name, "<A>"), (b_cl.name, "<B>"),
                   (c_cl.name, "<C>"), (d_cl.name, "<D>")]
        aliases += [(name, f"<eq{j}>") for j, name in enumerate(eq)]
        aliases += [(name, f"<cq{j}>") for j, name in enumerate(cq)]
        aliases.append((fq, "<fq>"))
        for actual, alias in sorted(aliases, key=lambda kv: -len(kv[0])):
            text = text.replace(actual, alias)
        log_bytes = text.encode()

        metrics_all = [s.broker.metrics for s in (a_srv, b_srv, c_srv,
                                                  d_srv)]
        return {
            "seed": seed,
            "fingerprint": fingerprint,
            "nodes": 4,
            "store": "memory (private per node)",
            "replicate_factor": 2,
            "confirmed": sum(len(v) for v in confirmed.values()),
            "queues": len(eq) + len(cq) + 1,
            "join_moves": len(join_moves),
            "drain_a": a_report,
            "crashed": crashed.is_set(),
            "failover_promotions": failovers,
            "stale_epoch_refused": refusals,
            "evacuated": sum(m.lifecycle_queues_evacuated
                             for m in metrics_all),
            "evacuation_retries": sum(m.lifecycle_evacuation_retries
                                      for m in metrics_all),
            "rollbacks": sum(m.lifecycle_rollbacks for m in metrics_all),
            "join_rebalances": sum(m.lifecycle_join_rebalances
                                   for m in metrics_all),
            "stale_holders_cleared": sum(m.lifecycle_stale_holders_cleared
                                         for m in metrics_all),
            "lost": lost,
            "stream": stream,
            "log_bytes": log_bytes,
            "log_sha256": hashlib.sha256(log_bytes).hexdigest(),
            "violations": violations,
        }
    finally:
        clear()
        if control is not None:
            try:
                await control.stop()
            except Exception:
                pass
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        for part in (d_cl, d_srv, c_cl, c_srv, b_cl, b_srv, a_cl, a_srv):
            if part is not None:
                try:
                    await part.stop()
                except Exception:
                    pass


async def run_elastic_soak(seed: int) -> dict:
    """Elasticity chaos soak (``bench.py --elastic``): the same seeded
    episode — join-triggered rebalance, graceful drain to ``left``,
    kill -9 mid-drain, partition healing into a fenced stale owner — run
    TWICE with the same seed. The report's ``violations`` list is empty
    iff every run held:

    1. **Zero confirmed loss** — every confirm-gated body is consumable
       from its queue's final holder, across a join move, two drains, a
       crash promotion, and a fenced partition.
    2. **Exactly one live holder per queue at quiesce** — no queue ends
       split-brained or orphaned.
    3. **Fencing works** — the healed stale owner's ships were refused
       (``lifecycle_stale_epoch_refused``) and it adopted the
       higher-epoch holdership instead of clobbering it.
    4. **Stream cursors resume contiguously** on the surviving node.
    5. **The decision/evacuation log is deterministic** — the two runs'
       normalized logs compare byte-identical, and non-trivially.
    """
    first = await _elastic_run(seed)
    second = await _elastic_run(seed)
    violations = list(first.pop("violations"))
    violations.extend(second.pop("violations"))
    log1 = first.pop("log_bytes")
    log2 = second.pop("log_bytes")
    if not log1:
        violations.append("first run produced an empty "
                          "decision/evacuation log")
    if log1 != log2:
        violations.append(
            "same-seed decision/evacuation logs differ between runs")
    return {
        "seed": seed,
        "runs": [first, second],
        "log_sha256": first.get("log_sha256"),
        "violations": violations,
    }


async def _stream_cursor_check(
    srv, sq: str, records: int, violations: list[str]
) -> dict:
    """Invariant 4: publish a stream, ack half under one tag, detach,
    reattach at "next" — deliveries must resume at committed+1 and run
    contiguously to the tail."""
    from ..amqp.properties import BasicProperties
    from ..client.client import AMQPClient

    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    try:
        pch = await conn.channel()
        await pch.confirm_select()
        await pch.queue_declare(
            sq, durable=True, arguments={"x-queue-type": "stream"})
        props = BasicProperties(delivery_mode=2)
        for i in range(records):
            pch.basic_publish(f"s{i:06d}".encode(), routing_key=sq,
                              properties=props)
        await pch.wait_unconfirmed_below(1, timeout=30)

        half = records // 2
        first_leg: list = []
        got_half = asyncio.Event()
        ch1 = await conn.channel()
        await ch1.basic_qos(prefetch_count=records + 8)

        def leg1(msg):
            first_leg.append((msg.delivery_tag, bytes(msg.body).decode()))
            if len(first_leg) == half:
                got_half.set()

        await ch1.basic_consume(
            sq, leg1, consumer_tag="soak-cursor",
            arguments={"x-stream-offset": "first"})
        await asyncio.wait_for(got_half.wait(), 15)
        # commit the cursor through record half-1, then detach
        ch1.basic_ack(first_leg[half - 1][0], multiple=True)
        await asyncio.sleep(0.3)  # let the commit land
        await ch1.basic_cancel("soak-cursor")

        second_leg: list = []
        done = asyncio.Event()
        ch2 = await conn.channel()
        await ch2.basic_qos(prefetch_count=records + 8)

        def leg2(msg):
            second_leg.append(bytes(msg.body).decode())
            if len(second_leg) >= records - half:
                done.set()

        await ch2.basic_consume(
            sq, leg2, consumer_tag="soak-cursor",
            arguments={"x-stream-offset": "next"})
        try:
            await asyncio.wait_for(done.wait(), 15)
        except asyncio.TimeoutError:
            pass
        expected = [f"s{i:06d}" for i in range(half, records)]
        resumed_ok = second_leg[:len(expected)] == expected \
            and len(second_leg) >= len(expected)
        if not resumed_ok:
            violations.append(
                f"stream cursor did not resume contiguously at committed+1 "
                f"(expected s{half:06d}.., got {second_leg[:3]})")
        return {
            "records": records,
            "committed_through": half - 1,
            "resumed_at": second_leg[0] if second_leg else None,
            "contiguous": resumed_ok,
        }
    finally:
        try:
            await conn.close()
        except Exception:
            pass


async def _key_shared_group_check(srv, qname: str, violations: list[str]) -> dict:
    """Invariant 7 (PR 13): a key-shared group member disconnecting with
    deliveries in flight must NOT reorder any key. Its records requeue and
    redeliver to the survivor before any later record of the same keys, so
    the survivor's per-key ack sequence is strictly increasing and the
    group ends complete (every published record acked exactly once)."""
    from ..client.client import AMQPClient

    keys = [f"k{i}" for i in range(4)]
    per_key_records = 6
    total = per_key_records * len(keys)
    group_args = {"x-group": "soak-ks", "x-group-type": "key-shared",
                  "x-stream-offset": "first"}

    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    victim = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    survivor = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    try:
        pch = await pub.channel()
        await pch.queue_declare(
            qname, durable=True, arguments={"x-queue-type": "stream"})
        await pch.exchange_declare(qname + "-x", "fanout")
        await pch.queue_bind(qname, qname + "-x", "")

        # the victim takes a prefetch window and never acks
        vch = await victim.channel()
        await vch.basic_qos(prefetch_count=6)
        victim_held = asyncio.Event()
        victim_got: list = []

        def victim_cb(msg):
            victim_got.append(msg.routing_key)
            if len(victim_got) >= 6:
                victim_held.set()

        await vch.basic_consume(qname, victim_cb, consumer_tag="ks-victim",
                                arguments=dict(group_args))

        sch = await survivor.channel()
        acked: list = []  # (key, seq) in ack order
        complete = asyncio.Event()

        def survivor_cb(msg):
            acked.append((msg.routing_key, int(bytes(msg.body))))
            sch.basic_ack(msg.delivery_tag)
            if len(acked) >= total:
                complete.set()

        await sch.basic_consume(qname, survivor_cb,
                                consumer_tag="ks-survivor",
                                arguments=dict(group_args))

        await pch.confirm_select()
        for seq in range(per_key_records):
            for key in keys:
                pch.basic_publish(str(seq).encode(), exchange=qname + "-x",
                                  routing_key=key)
        await pch.wait_unconfirmed_below(1, timeout=30)
        try:
            await asyncio.wait_for(victim_held.wait(), 15)
        except asyncio.TimeoutError:
            violations.append("key-shared: victim member never saturated "
                              "its prefetch window")
        early = len(acked)  # every key stuck to the victim: should be 0
        await victim.close()  # mid-flight disconnect: requeue + rebalance
        try:
            await asyncio.wait_for(complete.wait(), 15)
        except asyncio.TimeoutError:
            violations.append(
                f"key-shared: survivor drained only {len(acked)}/{total} "
                "records after the member disconnect")
        ordered = True
        per_key: dict[str, list] = {}
        for key, seq in acked:
            per_key.setdefault(key, []).append(seq)
        for key, seqs in per_key.items():
            if seqs != sorted(set(seqs)):
                ordered = False
                violations.append(
                    f"key-shared: key {key} acked out of order after "
                    f"redelivery: {seqs}")
        want = sorted(list(range(per_key_records)) * len(keys))
        if sorted(s for v in per_key.values() for s in v) != want:
            violations.append(
                "key-shared: records lost or duplicated across the "
                "disconnect")
        return {
            "records": total,
            "keys": len(keys),
            "victim_held": len(victim_got),
            "acked_before_disconnect": early,
            "per_key_ordered": ordered,
        }
    finally:
        for conn in (pub, victim, survivor):
            try:
                await conn.close()
            except Exception:
                pass


async def _tenant_run(seed: int) -> dict:
    """One noisy-neighbor episode on a three-tenant node. Returns a report
    plus the normalized tenancy decision-log bytes for same-seed
    comparison (run_tenant_soak runs this twice).

    Cast: ``aggr`` floods past a publish-rate quota (token bucket sized so
    the bucket gates on exactly the 16th publish and each registry tick
    refills exactly 8 publishes' worth of tokens); ``vict`` has no quota
    and must see clean paced latency, an untouched SLO budget, and a
    tenant-filtered firehose while the aggressor is parked; ``mem``
    breaches a memory-share floor with a confirmed backlog and only a
    drain lifts it. Every registry tick is harness-driven (the broker
    sweep is parked at 1 h), so the decision log is a pure function of
    message counts — byte-identical across same-seed runs."""
    import hashlib
    import json as json_mod

    from .. import events as events_mod
    from .. import tenancy as tenancy_mod
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..events.bus import EventBus, Firehose
    from ..slo import SLOSpec, attach_tenant_latency
    from ..slo.engine import SLOEngine
    from ..store.memory import MemoryStore
    from ..telemetry import TelemetryService
    from ..telemetry.alerts import default_rules as alert_defaults
    from ..tenancy.registry import TenantRegistry

    BODY = 1024
    COST = BODY + 512            # held-cost formula: body + flat overhead
    RATE = 8 * COST              # refill: exactly 8 publishes per tick
    BURST = 16 * COST            # bucket: the 16th publish closes the gate
    rounds = 2 + seed % 3        # drain rounds (8 held publishes each)
    extra = 8 * rounds           # flood depth beyond the gate
    MEM_BODY = 2048
    HIGH = 256 * 1024            # memory high watermark the shares read
    # mem's share = 65536: 40 x 2048 = 81920 breaches it; exit at 52428

    violations: list[str] = []

    async def until(predicate, timeout, what):
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate():
            if asyncio.get_event_loop().time() > deadline:
                violations.append(f"timeout waiting for {what}")
                return False
            await asyncio.sleep(0.02)
        return True

    broker = Broker(store=MemoryStore(),
                    message_sweep_interval_s=3600.0,  # manual ticks only
                    memory_high_watermark=HIGH,
                    flow_high_watermark=8 << 20)  # node ladder stays at 0
    # base (non-tenant) operator account: tenant users are confined to
    # their tenant's vhosts, so the "/" event/firehose consumer needs a
    # server-wide identity once tenant users force authentication on
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                       heartbeat_s=0, users={"ops": "ops-pw"})
    registry = TenantRegistry(broker)
    registry.define("aggr", {
        "vhosts": ["vaggr"], "users": {"aggr": "pw-a"},
        "quota": {"publish-rate": RATE, "publish-burst": BURST}})
    registry.define("vict", {"vhosts": ["vvict"], "users": {"vict": "pw-v"}})
    registry.define("mem", {
        "vhosts": ["vmem"], "users": {"mem": "pw-m"},
        "quota": {"memory-share": 0.25}})
    broker.tenancy = registry
    tenancy_mod.install(registry)

    # tenant-scoped SLOs: vict's latency objective gets its own histogram
    # (attach_tenant_latency) and an independent error budget the
    # aggressor must not be able to burn
    specs = [
        SLOSpec("vict-latency", "delivery-latency", threshold_ms=250.0,
                fast_windows=(5, 30), slow_windows=(60, 240),
                budget_window=240, tenant="vict"),
        SLOSpec("vict-publish", "publish-success",
                fast_windows=(5, 30), slow_windows=(60, 240),
                budget_window=240, tenant="vict"),
    ]
    engine = SLOEngine(specs)
    svc = TelemetryService(
        broker, interval_s=1.0, ring_ticks=64,
        rules=alert_defaults(backlog_growth=1e12, stall_ticks=10**6,
                             repl_lag=1e12, loop_lag_ms=1e12,
                             memory_stage=1e12),
        slo=engine)
    broker.telemetry = svc
    attach_tenant_latency(engine, registry)

    conns: list = []
    bus_events: list[dict] = []
    taps: list = []
    try:
        await srv.start()
        for vh in ("vaggr", "vvict", "vmem"):
            await broker.create_vhost(vh)

        # -- observability consumers FIRST (ops identity on "/"): the
        #    decision stream, one tenant-scoped union binding, and the
        #    vict-filtered firehose
        ops = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, username="ops", password="ops-pw")
        conns.append(ops)
        ech = await ops.channel()
        await ech.queue_declare("tev", exclusive=True)
        await ech.queue_bind("tev", "amq.chanamq.event", "tenant.throttle.*")
        await ech.queue_bind("tev", "amq.chanamq.event", "tenant.resume.*")
        await ech.queue_bind("tev", "amq.chanamq.event",
                             "tenant.aggr.queue.declared")

        def on_event(msg):
            bus_events.append(json_mod.loads(bytes(msg.body)))
            ech.basic_ack(msg.delivery_tag)

        await ech.basic_consume("tev", on_event, consumer_tag="soak-ev")

        fch = await ops.channel()
        await fch.queue_declare("tfh", exclusive=True)
        await fch.queue_bind("tfh", "amq.chanamq.trace", "publish.#")
        await fch.queue_bind("tfh", "amq.chanamq.trace", "publish")
        await fch.queue_bind("tfh", "amq.chanamq.trace", "deliver.#")

        def on_tap(msg):
            taps.append(msg.routing_key)
            fch.basic_ack(msg.delivery_tag)

        await fch.basic_consume("tfh", on_tap, consumer_tag="soak-fh")
        events_mod.install(EventBus(broker),
                           Firehose(broker, tenant_filter="vict"))

        # -- aggressor: 16 paced publishes exactly drain the burst; the
        #    16th spend lands tokens on 0 and closes the gate
        aggr = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="vaggr",
            username="aggr", password="pw-a")
        conns.append(aggr)
        ach = await aggr.channel()
        await ach.confirm_select()
        await ach.queue_declare("aq")
        for i in range(16):
            ach.basic_publish(b"a" * BODY, routing_key="aq")
            await ach.wait_unconfirmed_below(1, timeout=10)
        aggr_t = registry.tenants["aggr"]
        if not aggr_t.rate_gated:
            violations.append("aggressor bucket did not gate on the 16th "
                              f"publish (tokens={aggr_t.tokens})")
        # published=15: the counter increments after the gating spend, so
        # the 16th publish is in flight when the throttle is ledgered
        if not registry.decision_log or registry.decision_log[0] != {
                "decision": "throttle", "tenant": "aggr",
                "reason": "publish-rate", "tick": 0, "tokens": 0,
                "resident": 0, "floor": 0, "published": 15}:
            violations.append(
                f"unexpected first decision: {registry.decision_log[:1]}")

        # flood past the gate: every one of these parks at the hold gate
        for _ in range(extra):
            ach.basic_publish(b"a" * BODY, routing_key="aq")

        def held_publishes(tenant):
            # only publishes: the client's FlowOk reply to the advisory
            # Channel.Flow can FIFO-park behind a held publish too
            return sum(
                1 for c in tenant.conns for cmds in c._held.values()
                for cmd in cmds if type(cmd.method).__name__ == "Publish")

        await until(lambda: held_publishes(aggr_t) == extra, 10,
                    f"{extra} held aggressor publishes")

        # -- victim, while the aggressor is parked: paced publish->deliver
        #    latency plus its own SLO budget must be untouched
        vict = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="vvict",
            username="vict", password="pw-v")
        conns.append(vict)
        vch = await vict.channel()
        await vch.confirm_select()
        await vch.queue_declare("vq")
        loop = asyncio.get_event_loop()
        lat: list[float] = []
        got = asyncio.Event()

        def on_vict(msg):
            lat.append(loop.time() - t0)
            vch.basic_ack(msg.delivery_tag)
            got.set()

        await vch.basic_consume("vq", on_vict, consumer_tag="v")
        svc.sample_tick(1.0)  # latency baseline tick (delta buckets)
        for i in range(24):
            got.clear()
            t0 = loop.time()
            vch.basic_publish(b"v" * BODY, routing_key="vq")
            await asyncio.wait_for(got.wait(), 10)
        svc.sample_tick(1.0)
        svc.sample_tick(1.0)
        p99 = sorted(lat)[max(0, int(len(lat) * 0.99) - 1)]
        if p99 > 0.25:
            violations.append(
                f"victim paced p99 {p99 * 1000:.1f} ms > 250 ms while the "
                "aggressor was parked")
        budgets = engine.readiness_stamp()["budget_remaining"]
        for name in ("vict-latency", "vict-publish"):
            if budgets.get(name) != 1.0:
                violations.append(
                    f"victim SLO budget burned: {name}={budgets.get(name)}")

        # -- drain: each tick refills exactly 8 publishes' tokens -> the
        #    gate lifts, 8 held publishes release and re-close it
        for r in range(1, rounds + 1):
            registry.tick(1.0)
            remaining = extra - 8 * r
            await until(lambda want=remaining:
                        len(ach.unconfirmed) == want, 10,
                        f"drain round {r}: {remaining} unconfirmed left")
        registry.tick(1.0)  # final refill lifts the gate for good
        if aggr_t.gated:
            violations.append("aggressor still gated after the final tick")
        if aggr_t.throttles != rounds + 1:
            violations.append(
                f"aggressor throttles {aggr_t.throttles} != {rounds + 1}")

        # zero confirmed loss through the gate: everything the aggressor
        # ever published is consumable
        a_got: set[int] = set()
        a_done = asyncio.Event()

        def on_aggr(msg):
            a_got.add(msg.delivery_tag)
            ach.basic_ack(msg.delivery_tag)
            if len(a_got) >= 16 + extra:
                a_done.set()

        await ach.basic_consume("aq", on_aggr, consumer_tag="a")
        try:
            await asyncio.wait_for(a_done.wait(), 15)
        except asyncio.TimeoutError:
            violations.append(
                f"aggressor drained only {len(a_got)}/{16 + extra} after "
                "the gate lifted")

        # -- memory-share floor: a confirmed 80 KiB backlog breaches mem's
        #    64 KiB share at the next tick; held publishes stay parked (a
        #    memory floor never grants credit) until a consumer drains it
        mem = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="vmem",
            username="mem", password="pw-m")
        conns.append(mem)
        mch = await mem.channel()
        await mch.confirm_select()
        await mch.queue_declare("mq")
        for _ in range(40):
            mch.basic_publish(b"m" * MEM_BODY, routing_key="mq")
        await mch.wait_unconfirmed_below(1, timeout=10)
        mem_t = registry.tenants["mem"]
        registry.tick(1.0)
        if not mem_t.memory_gated:
            violations.append(
                f"memory share not gated at {mem_t.resident_bytes} resident")
        for _ in range(8):
            mch.basic_publish(b"m" * MEM_BODY, routing_key="mq")

        await until(lambda: held_publishes(mem_t) == 8, 10,
                    "8 held mem publishes")
        registry.tick(1.0)
        if not mem_t.memory_gated:
            violations.append("memory floor lifted without a drain")

        m_count = 0
        m_done = asyncio.Event()

        # a second channel: the consume must not queue behind the held
        # publishes (holds are per-channel FIFO by design)
        mch2 = await mem.channel()

        def on_mem(msg):
            nonlocal m_count
            m_count += 1
            mch2.basic_ack(msg.delivery_tag)
            if m_count >= 48:
                m_done.set()

        await mch2.basic_consume("mq", on_mem, consumer_tag="m")
        await until(lambda: registry.tenant_resident_bytes(mem_t) == 0,
                    15, "mem backlog drain")
        registry.tick(1.0)  # resident back under the exit ratio: resume
        if mem_t.memory_gated:
            violations.append("memory floor still pinned after the drain")
        try:
            await asyncio.wait_for(m_done.wait(), 15)
        except asyncio.TimeoutError:
            violations.append(
                f"mem delivered only {m_count}/48 after the floor lifted")

        # -- event-bus and firehose assertions (delivery is async: give
        #    the streams a bounded settle window)
        expected_events = 2 * rounds + 5
        await until(lambda: len(bus_events) >= expected_events, 10,
                    f"{expected_events} bus events")
        decisions = [ev["event"] for ev in bus_events
                     if not ev["event"].startswith("tenant.aggr.queue")
                     and ev["event"] != "queue.declared"]
        want = (["tenant.throttle.aggr"]
                + ["tenant.resume.aggr", "tenant.throttle.aggr"] * rounds
                + ["tenant.resume.aggr", "tenant.throttle.mem",
                   "tenant.resume.mem"])
        if decisions != want:
            violations.append(
                f"decision event stream mismatch: {decisions} != {want}")
        union = [ev for ev in bus_events if ev["event"] == "queue.declared"]
        if len(union) != 1 or union[0].get("tenant") != "aggr" \
                or union[0].get("queue") != "aq":
            violations.append(
                f"tenant-scoped union route broken: {union}")
        if any(".vict" in ev["event"] for ev in bus_events):
            violations.append("victim tenant saw gate decisions")
        await until(lambda: len(taps) >= 48, 10, "48 firehose taps")
        bad_taps = [t for t in taps if t not in ("publish", "deliver.vq")]
        if bad_taps:
            violations.append(
                f"vict-filtered firehose tapped foreign traffic: "
                f"{sorted(set(bad_taps))}")
        if taps.count("deliver.vq") != 24 or taps.count("publish") != 24:
            violations.append(
                f"firehose tap counts off: {len(taps)} total, "
                f"{taps.count('deliver.vq')} delivers")

        log_blob = json_mod.dumps(
            registry.decision_log, separators=(",", ":"),
            sort_keys=True).encode()
        return {
            "seed": seed,
            "rounds": rounds,
            "aggr_published": aggr_t.published_total(),
            "aggr_throttles": aggr_t.throttles,
            "victim_p99_ms": round(p99 * 1000, 2),
            "victim_budgets": {k: budgets.get(k) for k in budgets},
            "mem_throttles": mem_t.throttles,
            "decisions": len(registry.decision_log),
            "bus_events": len(bus_events),
            "firehose_taps": len(taps),
            "log_sha256": hashlib.sha256(log_blob).hexdigest(),
            "log_bytes": log_blob,
            "violations": violations,
        }
    finally:
        events_mod.install(None)
        tenancy_mod.install(None)
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        try:
            await srv.stop()
        except Exception:
            pass


async def run_tenant_soak(seed: int) -> dict:
    """Noisy-neighbor tenancy soak (``bench.py --tenant``): the seeded
    three-tenant episode run TWICE with the same seed. ``violations`` is
    empty iff every run held:

    1. **Quota throttles the aggressor, not the victim** — the token
       bucket gates on exactly the 16th publish, each registry tick
       releases exactly 8 held publishes, and the victim's paced p99
       stays under 250 ms with its tenant SLO budgets at 1.0.
    2. **Zero confirmed loss through the gates** — every held publish is
       eventually released, confirmed and consumable.
    3. **The memory-share floor is drain-lifted only** — held publishes
       never execute while the floor is pinned.
    4. **Tenant-scoped observability is exact** — the decision event
       stream, the ``tenant.<name>.*`` union route and the
       tenant-filtered firehose each carry exactly the expected traffic.
    5. **The decision log is deterministic** — the two runs' normalized
       logs compare byte-identical, and non-trivially.
    """
    first = await _tenant_run(seed)
    second = await _tenant_run(seed)
    violations = list(first.pop("violations"))
    violations.extend(second.pop("violations"))
    log1 = first.pop("log_bytes")
    log2 = second.pop("log_bytes")
    if not log1:
        violations.append("first run produced an empty decision log")
    if log1 != log2:
        violations.append("same-seed tenancy decision logs differ")
    return {
        "seed": seed,
        "runs": [first, second],
        "log_sha256": first.get("log_sha256"),
        "violations": violations,
    }


async def run_tenant_churn(cycles: int = 10000, *,
                           amqp_every: int = 100) -> dict:
    """Tenant-churn leak check (``bench.py --tenant-churn``): ``cycles``
    define/remove rounds against a live registry — every ``amqp_every``-th
    round also creates the tenant's vhost, authenticates as its user,
    declares/publishes confirmed, disconnects and deletes the vhost. At
    the end every registry index, auth view, accounted byte and vhost
    must be exactly back at baseline: a surviving slot is a leak in the
    define/remove or detach bookkeeping."""
    from .. import tenancy as tenancy_mod
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..store.memory import MemoryStore
    from ..tenancy.registry import TenantRegistry

    broker = Broker(store=MemoryStore(), message_sweep_interval_s=3600.0,
                    flow_high_watermark=8 << 20)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                       heartbeat_s=0)
    registry = TenantRegistry(broker)
    broker.tenancy = registry
    tenancy_mod.install(registry)
    violations: list[str] = []
    baseline_vhosts = None
    amqp_cycles = 0
    try:
        await srv.start()
        baseline_vhosts = set(broker.vhosts)
        for i in range(cycles):
            name, vh, user = f"t{i}", f"vt{i}", f"u{i}"
            tenant = registry.define(name, {
                "vhosts": [vh], "users": {user: f"pw{i}"},
                "acls": {user: {vh: ["configure", "write", "read"]}},
                "quota": {"publish-rate": 4096, "max-queues": 4}})
            if i % amqp_every == 0:
                await broker.create_vhost(vh)
                conn = await AMQPClient.connect(
                    "127.0.0.1", srv.bound_port, vhost=vh,
                    username=user, password=f"pw{i}")
                try:
                    if len(tenant.conns) != 1:
                        violations.append(
                            f"cycle {i}: authenticated connection not "
                            f"attached ({len(tenant.conns)} attached)")
                    ch = await conn.channel()
                    await ch.confirm_select()
                    await ch.queue_declare(f"q{i}")
                    for _ in range(3):
                        ch.basic_publish(b"t" * 512, routing_key=f"q{i}")
                    await ch.wait_unconfirmed_below(1, timeout=10)
                    # explicit delete: vhost teardown drops structures but
                    # the accounting gate is the queue-deletion path
                    await ch.queue_delete(f"q{i}")
                finally:
                    await conn.close()
                deadline = asyncio.get_event_loop().time() + 10
                while tenant.conns and \
                        asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.005)
                if tenant.conns:
                    violations.append(
                        f"cycle {i}: connection never detached")
                    break
                await broker.delete_vhost(vh)
                amqp_cycles += 1
            if not registry.remove(name):
                violations.append(f"cycle {i}: remove({name!r}) missed")
                break

        # settle: every registry slot, auth view and accounted byte must
        # be exactly at baseline
        if registry.tenants or registry.by_vhost or registry.by_user:
            violations.append(
                f"registry slots leaked: {len(registry.tenants)} tenants, "
                f"{len(registry.by_vhost)} vhosts, "
                f"{len(registry.by_user)} users")
        if registry.auth_users(None) is not None:
            violations.append("auth_users view retains churned users")
        if registry.auth_permissions(None) is not None:
            violations.append("auth_permissions view retains allowlists")
        leaked = broker.resident_bytes + broker.held_bytes
        if leaked:
            violations.append(
                f"accounted-bytes leak: resident={broker.resident_bytes} "
                f"held={broker.held_bytes}")
        if set(broker.vhosts) != baseline_vhosts:
            violations.append(
                f"vhosts not at baseline: "
                f"{sorted(set(broker.vhosts) - baseline_vhosts)}")
        if registry.decision_log:
            violations.append(
                f"{len(registry.decision_log)} spurious gate decisions "
                "during churn")
        if broker.metrics.tenancy_quota_refusals_total:
            violations.append(
                f"{broker.metrics.tenancy_quota_refusals_total} spurious "
                "quota refusals during churn")
        return {
            "cycles": cycles,
            "amqp_cycles": amqp_cycles,
            "leaked_bytes": leaked,
            "live_vhosts": len(broker.vhosts),
            "registry_slots": (len(registry.tenants)
                               + len(registry.by_vhost)
                               + len(registry.by_user)),
            "violations": violations,
        }
    finally:
        tenancy_mod.install(None)
        try:
            await srv.stop()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# delivery-semantics soak: Tx kill -9 at the WAL commit boundary +
# TTL-expiry dead-lettering under seeded store faults
# ---------------------------------------------------------------------------


async def _tx_kill_run(seed: int) -> dict:
    """One seeded transaction workload ending in a simulated SIGKILL
    between Tx.Commit receipt and the WAL group commit.

    A client runs a seeded mix of commits and rollbacks against a
    WAL-backed broker; at the seeded kill index the store is "killed"
    the instant the commit's tx_batch is sealed — before a single byte
    of it can reach the segment file (the commit task is cancelled and
    the write executors torn down synchronously, so the crash point is
    a pure function of the seed). A fresh broker over the same directory
    must then recover exactly the committed transactions: zero confirmed
    loss, no post-rollback ghosts, and the killed transaction absent
    as a whole (all-or-nothing)."""
    import random
    import shutil
    import tempfile
    from zlib import crc32

    from ..amqp.properties import BasicProperties
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..store.sqlite import SqliteStore
    from ..wal import WalStore

    rng = random.Random((seed * 1_000_003) ^ crc32(b"tx-commit-kill"))
    root = tempfile.mkdtemp(prefix="chanamq-semsoak-")
    db = root + "/store.db"
    log: list = []
    violations: list[str] = []
    committed: list[str] = []
    rolled_back: list[str] = []
    killed_bodies: list[str] = []
    kill_at = 6 + rng.randrange(3)
    try:
        store = WalStore(SqliteStore(db), flush_ms=1.0,
                         checkpoint_ms=3_600_000.0)
        killed = asyncio.Event()
        orig_seal = store.tx_seal
        orig_flush = store.flush
        armed = False

        def seal_and_die():
            # SIGKILL simulation, synchronous with the seal: nothing that
            # happens after this line may reach disk
            store._commit_task.cancel()
            store._checkpoint_task.cancel()
            store._inner._closed = True
            store._executor.shutdown(wait=True)
            store._inner._executor.shutdown(wait=False)
            lsn = orig_seal()
            killed.set()
            return lsn

        def flush(intervals=None):
            if not killed.is_set():
                return orig_flush(intervals)

            async def _dead():
                # the killed process writes nothing durable; completing
                # the barrier (vs hanging) only lets the doomed coroutine
                # unwind so teardown is clean — the disk state is already
                # frozen by seal_and_die
                return None
            return _dead()

        srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                           store=store)
        await srv.start()
        conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await conn.channel()
        await ch.queue_declare("txq", durable=True)
        await ch.tx_select()
        persistent = BasicProperties(delivery_mode=2)
        commit_task = None
        for i in range(12):
            bodies = ["tx%d-%d" % (i, j)
                      for j in range(1 + rng.randrange(3))]
            roll = rng.random() < 0.3
            if i == kill_at:
                armed = True
                store.tx_seal = seal_and_die
                store.flush = flush
            for body in bodies:
                ch.basic_publish(body.encode(), routing_key="txq",
                                 properties=persistent)
            if i == kill_at:
                killed_bodies = bodies
                commit_task = asyncio.ensure_future(ch.tx_commit())
                await asyncio.wait_for(killed.wait(), timeout=15)
                log.append(["kill", i, len(bodies)])
                break
            if roll:
                await ch.tx_rollback()
                rolled_back.extend(bodies)
                log.append(["rollback", i, len(bodies)])
            else:
                await ch.tx_commit()
                committed.extend(bodies)
                log.append(["commit", i, len(bodies)])
        if not armed or not killed.is_set():
            violations.append("kill rule never fired")
        if commit_task is not None:
            commit_task.cancel()
        try:
            await asyncio.wait_for(conn.close(), timeout=2)
        except Exception:
            pass
        try:
            await asyncio.wait_for(srv.stop(), timeout=3)
        except Exception:
            pass

        # ---- recovery: a fresh broker over the same directory ----
        store2 = WalStore(SqliteStore(db), flush_ms=1.0,
                          checkpoint_ms=3_600_000.0)
        srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                            store=store2)
        await srv2.start()
        try:
            conn2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
            ch2 = await conn2.channel()
            got: list[str] = []
            deadline = asyncio.get_event_loop().time() + 5.0
            while asyncio.get_event_loop().time() < deadline:
                msg = await ch2.basic_get("txq", no_ack=True)
                if msg is None:
                    if len(got) >= len(committed):
                        break
                    await asyncio.sleep(0.02)
                    continue
                got.append(bytes(msg.body).decode())
            missing = [b for b in committed if b not in got]
            if missing:
                violations.append(
                    f"confirmed loss: {len(missing)} committed bodies "
                    f"missing after recovery ({missing[:3]}...)")
            ghosts = [b for b in got if b in rolled_back]
            if ghosts:
                violations.append(
                    f"post-rollback ghosts recovered: {ghosts[:3]}")
            kill_recovered = [b for b in killed_bodies if b in got]
            if kill_recovered and len(kill_recovered) != len(killed_bodies):
                violations.append(
                    "killed tx partially recovered: "
                    f"{len(kill_recovered)}/{len(killed_bodies)} — "
                    "the tx_batch boundary is torn")
            if got != committed + kill_recovered:
                violations.append(
                    f"recovered sequence diverges: got {len(got)} "
                    f"expected {len(committed)}")
            await conn2.close()
            log.append(["recovered", len(got), len(kill_recovered)])
        finally:
            await srv2.stop()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "kill_at": kill_at,
        "committed": len(committed),
        "rolled_back": len(rolled_back),
        "killed": len(killed_bodies),
        "log": log,
        "violations": violations,
    }


async def _ttl_dlx_run(seed: int) -> dict:
    """TTL-expiry dead-lettering under a seeded degraded-storage window
    (the single-node stand-in for a partition: flushes dropped, writes
    delayed — the durability path is unreachable, the broker keeps
    running). Every expired body must arrive in the DLQ exactly once
    with exactly one x-death entry."""
    import random
    from zlib import crc32

    from ..amqp.properties import BasicProperties
    from ..broker.broker import Broker
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..store.memory import MemoryStore
    from .store import ChaosStore

    rng = random.Random((seed * 1_000_003) ^ crc32(b"ttl-dlx-partition"))
    messages = 40
    plan = FaultPlan(seed, [
        FaultRule(name="dlx-partition-flush", kind="drop",
                  sites=["store.flush"], after=2, count=4),
        FaultRule(name="dlx-partition-latency", kind="latency",
                  sites=["store.write", "store.delete"],
                  probability=0.25, delay_ms=2),
    ])
    install(plan)
    violations: list[str] = []
    try:
        broker = Broker(message_sweep_interval_s=0.05,
                        store=ChaosStore(MemoryStore(), _LazyRuntime()))
        srv = BrokerServer(broker=broker, host="127.0.0.1", port=0,
                           heartbeat_s=0)
        await srv.start()
        try:
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            ch = await conn.channel()
            await ch.exchange_declare("soak_dlx", "fanout", durable=True)
            await ch.queue_declare("soak_dlq", durable=True)
            await ch.queue_bind("soak_dlq", "soak_dlx", "")
            # durable queue + persistent bodies so expiry/dead-letter
            # bookkeeping actually crosses the (faulted) store sites
            await ch.queue_declare("soak_ttl", durable=True, arguments={
                "x-message-ttl": 60,
                "x-dead-letter-exchange": "soak_dlx",
                "x-dead-letter-routing-key": "dead"})
            for i in range(messages):
                props = BasicProperties(delivery_mode=2)
                if rng.random() < 0.4:  # per-message TTL below queue TTL
                    props = BasicProperties(delivery_mode=2, expiration="30")
                ch.basic_publish(b"dl%d" % i, routing_key="soak_ttl",
                                 properties=props)
            counts: dict = {}
            deadline = asyncio.get_event_loop().time() + 10.0
            while (sum(counts.values()) < messages
                   and asyncio.get_event_loop().time() < deadline):
                msg = await ch.basic_get("soak_dlq", no_ack=True)
                if msg is None:
                    await asyncio.sleep(0.02)
                    continue
                body = bytes(msg.body).decode()
                counts[body] = counts.get(body, 0) + 1
                deaths = (msg.properties.headers or {}).get("x-death") or []
                if len(deaths) != 1 or deaths[0].get("count") != 1:
                    violations.append(
                        f"{body}: x-death not exactly-once: {deaths}")
                elif deaths[0].get("reason") != "expired":
                    violations.append(
                        f"{body}: wrong death reason {deaths[0]}")
            expected = {"dl%d" % i for i in range(messages)}
            missing = sorted(expected - set(counts))
            dupes = sorted(b for b, n in counts.items() if n > 1)
            if missing:
                violations.append(
                    f"{len(missing)} expired bodies never dead-lettered "
                    f"({missing[:3]}...)")
            if dupes:
                violations.append(f"duplicate dead-letters: {dupes[:3]}")
            if broker.metrics.dlx_expired != messages:
                violations.append(
                    f"dlx_expired={broker.metrics.dlx_expired}, "
                    f"expected {messages}")
            dead_lettered = sum(counts.values())
            await conn.close()
        finally:
            await srv.stop()
    finally:
        clear()
    return {
        "messages": messages,
        "dead_lettered": dead_lettered,
        "fires": plan.total_fires,
        "violations": violations,
    }


async def run_semantics_soak(seed: int) -> dict:
    """Delivery-semantics chaos soak (ISSUE 17): both seeded rules run
    TWICE with the same seed and their normalized reports must serialize
    byte-identically — the fault schedule, the tx mix, the kill index and
    the recovery outcome are all pure functions of the seed."""
    import json as _json

    tx1 = await _tx_kill_run(seed)
    tx2 = await _tx_kill_run(seed)
    dlx1 = await _ttl_dlx_run(seed)
    dlx2 = await _ttl_dlx_run(seed)

    violations: list[str] = []
    for tag, run in (("tx", tx1), ("tx-repeat", tx2),
                     ("ttl-dlx", dlx1), ("ttl-dlx-repeat", dlx2)):
        violations.extend(f"{tag}: {v}" for v in run["violations"])

    def normalize(run: dict) -> str:
        return _json.dumps(
            {k: v for k, v in run.items() if k != "violations"},
            sort_keys=True)

    if normalize(tx1) != normalize(tx2):
        violations.append("same-seed tx-kill runs are not byte-identical")
    if normalize(dlx1) != normalize(dlx2):
        violations.append("same-seed ttl-dlx runs are not byte-identical")
    return {
        "seed": seed,
        "tx": tx1,
        "ttl_dlx": dlx1,
        "deterministic": normalize(tx1) == normalize(tx2)
        and normalize(dlx1) == normalize(dlx2),
        "violations": violations,
    }


# ---------------------------------------------------------------------------
# Federation soak (PR 19): two clusters, a severed link, a failed-over
# consumer, a heal — zero confirmed loss, contiguous cursor resume, no
# post-settle duplicates, and a seed-deterministic link transition log.
# ---------------------------------------------------------------------------

def _federation_sever_plan(seed: int) -> FaultPlan:
    """Every ship and every reconnect attempt fails while installed: a
    hard link sever at the federation seams (transport untouched, so the
    intra-broker clients keep working)."""
    return FaultPlan(seed, [
        FaultRule(name="sever-ship", kind="error", sites=["fed.ship"]),
        FaultRule(name="sever-connect", kind="error",
                  sites=["fed.connect"]),
    ])


async def _federation_run(seed: int) -> dict:
    """One seeded two-cluster run. Cluster A owns stream ``fq`` and a
    federation link to cluster B; a consumer on A commits a cursor, the
    link is severed mid-stream, the consumer fails over to B's mirror and
    resumes from the mirrored cursor, the link heals and the backlog
    ships. Returns a wall-clock-free report the determinism gate can
    compare byte-for-byte across same-seed runs."""
    import random as _random
    from zlib import crc32

    from ..amqp.properties import BasicProperties
    from ..broker.server import BrokerServer
    from ..client.client import AMQPClient
    from ..federation import FederationService
    from ..store.memory import MemoryStore

    rng = _random.Random((seed * 1_000_003) ^ crc32(b"federation"))
    violations: list[str] = []
    phase1 = 40 + rng.randrange(20)   # records before the sever
    phase2 = 30 + rng.randrange(20)   # records published while severed
    total = phase1 + phase2
    commit_k = phase1 // 2            # cursor committed through this index
    qname = "fq"
    cursor = "fed-cursor"

    async def eventually(predicate, timeout=15.0, what="condition"):
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate():
            if asyncio.get_event_loop().time() > deadline:
                violations.append(f"timed out waiting for {what}")
                return False
            await asyncio.sleep(0.02)
        return True

    # an empty seeded plan keeps chaos.backoff_rng() deterministic for
    # the whole run, including the healed phase
    install(FaultPlan(seed, []))
    b_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await b_srv.start()
    fed_b = FederationService(b_srv.broker, node_name="cluster-b", port=0)
    await fed_b.start()
    a_srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                         store=MemoryStore())
    await a_srv.start()
    fed_a = FederationService(
        a_srv.broker, node_name="cluster-a", port=0,
        retry_s=0.05, idle_s=0.05,
        links=[{"name": "to-b", "host": "127.0.0.1", "port": fed_b.port,
                "queues": [qname], "window": 4}])
    await fed_a.start()
    link = fed_a.links[0]
    report: dict = {}
    try:
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        pch = await conn.channel()
        await pch.confirm_select()
        # small segments so the run seals (and ships) many of them
        await pch.queue_declare(qname, durable=True, arguments={
            "x-queue-type": "stream",
            "x-stream-max-segment-size-bytes": 256})
        props = BasicProperties(delivery_mode=2)
        for i in range(phase1):
            pch.basic_publish(f"f{i:06d}".encode(), routing_key=qname,
                              properties=props)
        await pch.wait_unconfirmed_below(1, timeout=30)

        # consume on A and commit the cursor through commit_k
        got: list = []
        half_done = asyncio.Event()
        ch1 = await conn.channel()
        await ch1.basic_qos(prefetch_count=total + 8)

        def on_a(msg):
            got.append((msg.delivery_tag, bytes(msg.body).decode()))
            if len(got) == commit_k + 1:
                half_done.set()

        await ch1.basic_consume(qname, on_a, consumer_tag=cursor,
                                arguments={"x-stream-offset": "first"})
        await asyncio.wait_for(half_done.wait(), 15)
        ch1.basic_ack(got[commit_k][0], multiple=True)
        await asyncio.sleep(0.2)  # let the coalesced commit flush
        await ch1.basic_cancel(cursor)

        a_queue = a_srv.broker.get_queue("/", qname)
        b_queue_next = lambda: (  # noqa: E731
            b_srv.broker.vhosts["/"].queues.get(qname).next_offset
            if b_srv.broker.vhosts["/"].queues.get(qname) else 0)
        # quiesce: every sealed segment shipped, cursor mirrored — the
        # sever point is then a pure function of the seed, not of timing
        sealed_tail = a_queue._active_base
        await eventually(lambda: b_queue_next() >= sealed_tail,
                         what="pre-sever ship quiesce")
        # stream offsets are 1-based: body f{i} lives at offset i+1,
        # so acking through got[commit_k] commits offset commit_k + 1
        await eventually(
            lambda: (b_srv.broker.vhosts["/"].queues.get(qname) is not None
                     and b_srv.broker.vhosts["/"].queues[qname]
                     .committed.get(cursor) == commit_k + 1),
            what="cursor mirror")
        pre_sever_next = b_queue_next()

        # -- sever the link and keep publishing ----------------------------
        install(_federation_sever_plan(seed))
        for i in range(phase1, total):
            pch.basic_publish(f"f{i:06d}".encode(), routing_key=qname,
                              properties=props)
        await pch.wait_unconfirmed_below(1, timeout=30)
        link.wake()
        await eventually(lambda: link.state == "down", what="link down")
        if b_queue_next() != pre_sever_next:
            violations.append(
                f"severed link still shipped: mirror next "
                f"{b_queue_next()} != {pre_sever_next}")

        # -- fail the consumer group over to the mirror --------------------
        b_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        b_ch = await b_conn.channel()
        await b_ch.basic_qos(prefetch_count=total + 8)
        failover: list = []
        failover_caught_up = asyncio.Event()

        def on_b(msg):
            failover.append(bytes(msg.body).decode())
            if len(failover) >= total - commit_k - 1:
                failover_caught_up.set()

        await b_ch.basic_consume(qname, on_b, consumer_tag=cursor,
                                 arguments={"x-stream-offset": "next"})
        # the mirror can only serve what shipped before the sever:
        # offsets commit_k + 2 .. pre_sever_next - 1
        await eventually(
            lambda: len(failover) >= pre_sever_next - commit_k - 2,
            what="failover consumer catch-up to severed tail")
        resumed_at = failover[0] if failover else None
        if resumed_at != f"f{commit_k + 1:06d}":
            violations.append(
                f"failover did not resume at committed+1: got {resumed_at}")

        # -- heal: backlog ships, mirror converges on the full stream ------
        install(FaultPlan(seed, []))
        link.wake()
        await eventually(lambda: link.state == "up", what="link heal")
        # seal A's active segment so the tail records become shippable
        if a_queue._active:
            a_queue._seal_active()
        link.wake()
        await eventually(lambda: b_queue_next() >= total,
                         what="post-heal backlog ship")
        try:
            await asyncio.wait_for(failover_caught_up.wait(), 15)
        except asyncio.TimeoutError:
            violations.append(
                f"failover consumer saw {len(failover)}/{total - commit_k - 1}"
                " records after heal")

        # -- invariants -----------------------------------------------------
        expected = [f"f{i:06d}" for i in range(commit_k + 1, total)]
        if failover[:len(expected)] != expected:
            violations.append(
                f"failover delivery not contiguous: got {failover[:3]}.. "
                f"expected {expected[:3]}..")
        settle_len = len(failover)
        await asyncio.sleep(0.4)  # observation window
        if len(failover) != settle_len:
            violations.append(
                f"{len(failover) - settle_len} deliveries after settle")
        dupes = {b for b in failover if failover.count(b) > 1}
        if dupes:
            violations.append(f"duplicate failover deliveries: "
                              f"{sorted(dupes)[:3]}")

        # zero confirmed loss: a fresh reader of the mirror sees every
        # confirmed record, in order
        mirror: list = []
        mirror_done = asyncio.Event()
        m_ch = await b_conn.channel()
        await m_ch.basic_qos(prefetch_count=total + 8)

        def on_mirror(msg):
            mirror.append(bytes(msg.body).decode())
            if len(mirror) >= total:
                mirror_done.set()

        await m_ch.basic_consume(qname, on_mirror, consumer_tag="fed-audit",
                                 arguments={"x-stream-offset": "first"})
        try:
            await asyncio.wait_for(mirror_done.wait(), 15)
        except asyncio.TimeoutError:
            pass
        if mirror != [f"f{i:06d}" for i in range(total)]:
            violations.append(
                f"mirror lost confirmed records: {len(mirror)}/{total}")

        metrics = a_srv.broker.metrics
        report = {
            "records": total,
            "committed_through": commit_k,
            "pre_sever_next": pre_sever_next,
            "resumed_at": resumed_at,
            "mirror_records": len(mirror),
            "segments_shipped": metrics.federation_segments_shipped,
            "resumes": metrics.federation_resumes,
            "transitions": fed_a.transition_log(),
        }
        await b_conn.close()
        await conn.close()
    finally:
        await fed_a.stop()
        await a_srv.stop()
        await fed_b.stop()
        await b_srv.stop()
        clear()
    report["violations"] = violations
    return report


async def run_federation_soak(seed: int) -> dict:
    """Federation chaos soak: the seeded sever/heal run executes TWICE
    and the normalized reports (violations aside) must serialize
    byte-identically — the publish mix, the sever point and the link
    transition log are all pure functions of the seed."""
    import json as _json

    one = await _federation_run(seed)
    two = await _federation_run(seed)
    violations = list(one["violations"])
    violations.extend(f"repeat: {v}" for v in two["violations"])

    def normalize(run: dict) -> str:
        return _json.dumps(
            {k: v for k, v in run.items() if k != "violations"},
            sort_keys=True)

    deterministic = normalize(one) == normalize(two)
    if not deterministic:
        violations.append("same-seed federation runs are not byte-identical")
    return {
        "seed": seed,
        "run": one,
        "deterministic": deterministic,
        "violations": violations,
    }
