"""L4: cluster services — id generation, membership, ownership, RPC.

Rebuilds the capability of the reference's Akka-cluster control plane
(GlobalNodeIdService singleton, cluster sharding, distributed pub-sub) on a
pod-style multi-host model: consistent-hash entity ownership, host-to-host
RPC over TCP, a lease-based node-id singleton, and heartbeat membership
(SURVEY.md §5 "distributed communication backend", §7.1).
"""
