"""Content-header frame and BasicProperties presence-flag codec.

Capability parity with the reference's content-header model
(chana-mq-base .../model/BasicProperties.scala:42-153,
 .../model/AMQContentHeader.scala:10-61): a HEADER frame payload is
class-id(2) weight(2)=0 body-size(8) property-flags then property values;
property flags are 15-bit words whose low bit signals a continuation word.
BasicProperties has 14 optional fields (content-type .. cluster-id).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, fields as dc_fields
from io import BytesIO
from typing import Any, BinaryIO, Optional

from . import value_codec as vc
from .constants import ClassId

# (field-name, flag-bit, domain); order is the wire order.
_PROPERTY_SPEC: tuple[tuple[str, int, str], ...] = (
    ("content_type", 15, "shortstr"),
    ("content_encoding", 14, "shortstr"),
    ("headers", 13, "table"),
    ("delivery_mode", 12, "octet"),
    ("priority", 11, "octet"),
    ("correlation_id", 10, "shortstr"),
    ("reply_to", 9, "shortstr"),
    ("expiration", 8, "shortstr"),
    ("message_id", 7, "shortstr"),
    ("timestamp", 6, "longlong"),
    ("type", 5, "shortstr"),
    ("user_id", 4, "shortstr"),
    ("app_id", 3, "shortstr"),
    ("cluster_id", 2, "shortstr"),
)

DELIVERY_MODE_TRANSIENT = 1
DELIVERY_MODE_PERSISTENT = 2


@dataclass(slots=True)
class BasicProperties:
    content_type: Optional[str] = None
    content_encoding: Optional[str] = None
    headers: Optional[dict[str, Any]] = None
    delivery_mode: Optional[int] = None
    priority: Optional[int] = None
    correlation_id: Optional[str] = None
    reply_to: Optional[str] = None
    expiration: Optional[str] = None
    message_id: Optional[str] = None
    timestamp: Optional[int] = None
    type: Optional[str] = None
    user_id: Optional[str] = None
    app_id: Optional[str] = None
    cluster_id: Optional[str] = None

    @property
    def is_persistent(self) -> bool:
        return self.delivery_mode == DELIVERY_MODE_PERSISTENT

    def expiration_ms(self) -> Optional[int]:
        """Per-message TTL: the expiration property is a shortstr of millis."""
        if not self.expiration:
            return None
        try:
            return int(self.expiration)
        except ValueError:
            return None

    # -- codec ------------------------------------------------------------

    def write_properties(self, out: BinaryIO) -> None:
        flags = 0
        for name, bit, _ in _PROPERTY_SPEC:
            if getattr(self, name) is not None:
                flags |= 1 << bit
        # Single flag word suffices: 14 properties fit in one 15-bit word, so
        # the continuation bit (bit 0) is never set for basic-class content.
        vc.write_short(out, flags)
        for name, bit, domain in _PROPERTY_SPEC:
            value = getattr(self, name)
            if value is None:
                continue
            if domain == "shortstr":
                vc.write_shortstr(out, value)
            elif domain == "octet":
                vc.write_octet(out, value)
            elif domain == "longlong":
                vc.write_longlong(out, value)
            elif domain == "table":
                vc.write_table(out, value)

    @classmethod
    def read_properties(cls, stream: BinaryIO) -> "BasicProperties":
        # Collect flag words, honoring the continuation bit.
        flag_words = [vc.read_short(stream)]
        while flag_words[-1] & 0x0001:
            flag_words.append(vc.read_short(stream))
        props = cls()
        for name, bit, domain in _PROPERTY_SPEC:
            if not flag_words[0] & (1 << bit):
                continue
            if domain == "shortstr":
                setattr(props, name, vc.read_shortstr(stream))
            elif domain == "octet":
                setattr(props, name, vc.read_octet(stream))
            elif domain == "longlong":
                setattr(props, name, vc.read_longlong(stream))
            elif domain == "table":
                setattr(props, name, vc.read_table(stream))
        return props

    # -- header frame payload ---------------------------------------------

    def encode_header(self, body_size: int) -> bytes:
        out = BytesIO()
        out.write(struct.pack(">HHQ", ClassId.BASIC, 0, body_size))
        self.write_properties(out)
        return out.getvalue()

    @staticmethod
    def decode_header(payload: bytes) -> tuple[int, int, "BasicProperties"]:
        """Decode a HEADER-frame payload -> (class_id, body_size, properties).

        Hot loop: the two overwhelmingly common property shapes — no
        properties, and delivery-mode only — decode without the generic
        flag-walk."""
        if len(payload) < 14:
            raise ValueError("content header shorter than 14 bytes")
        class_id = (payload[0] << 8) | payload[1]
        body_size = int.from_bytes(payload[4:12], "big")
        flags = (payload[12] << 8) | payload[13]
        if flags == 0 and len(payload) == 14:
            return class_id, body_size, BasicProperties()
        if flags == 0x1000 and len(payload) == 15:  # delivery-mode only
            return class_id, body_size, BasicProperties(delivery_mode=payload[14])
        stream = BytesIO(payload)
        stream.seek(12)
        props = BasicProperties.read_properties(stream)
        return class_id, body_size, props

    def copy(self) -> "BasicProperties":
        values = {f.name: getattr(self, f.name) for f in dc_fields(self)}
        if values.get("headers") is not None:
            values["headers"] = dict(values["headers"])
        return BasicProperties(**values)
