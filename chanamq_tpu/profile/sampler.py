"""Off-loop sampling profiler + event-loop stall watchdog.

One daemon thread does both jobs:

- at ``chana.mq.profile.sample-hz`` it snapshots the event-loop thread's
  stack via ``sys._current_frames()`` and folds it into a bounded
  ``stack -> count`` table (flamegraph collapsed format on read);
- between samples it checks the loop heartbeat the runtime's on-loop
  task writes: a beat older than ``slow-callback-ms`` means the loop is
  pinned inside one callback, so the watchdog captures that callback's
  live stack *while it runs* and, once the beat resumes, records the
  episode (duration + folded stack) into a bounded ring, emits a
  structured JSON log line, and bumps ``profile_slow_callbacks_total``
  — the existing loop-lag telemetry gets names, not just lag numbers.

Sampling happens entirely off-loop; the hot path never sees it. The GIL
grants the sampler a slice every switch interval (~5 ms), so stalls of
watchdog magnitude cannot hide from it.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from collections import deque

from .. import events

log = logging.getLogger("chanamq.profile")

# folded-stack table cap: beyond this, new unique stacks fold into the
# overflow bucket instead of growing memory without bound
_MAX_STACKS = 4096
_OVERFLOW_KEY = "<stack-table-full>"


def fold_stack(frame, max_depth: int = 64) -> str:
    """Collapse a frame chain into ``root;...;leaf`` with
    ``name (file:line)`` entries — flamegraph.pl's collapsed format."""
    parts: list[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        fname = code.co_filename.rsplit("/", 1)[-1]
        parts.append(f"{code.co_name} ({fname}:{frame.f_lineno})")
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts) if parts else "<no-frames>"


class Sampler(threading.Thread):
    def __init__(self, runtime) -> None:
        super().__init__(name="chanamq-profile-sampler", daemon=True)
        self.runtime = runtime
        hz = runtime.sample_hz
        slow_ms = runtime.slow_callback_ms
        if hz > 0:
            self.interval = 1.0 / hz
        else:
            # watchdog-only cadence: check at a quarter of the threshold
            self.interval = max(slow_ms / 4000.0, 0.01)
        self.stacks: dict[str, int] = {}
        self.samples = 0
        self.ring: deque = deque(maxlen=runtime.ring_size)
        self.slow_count = 0
        self._stop = threading.Event()
        # in-flight stall episode: (first-seen beat, captured stack, max lag)
        self._stall_beat = 0
        self._stall_stack = ""
        self._stall_max_ns = 0

    def shutdown(self) -> None:
        self._stop.set()

    def run(self) -> None:
        rt = self.runtime
        sample = rt.sample_hz > 0
        slow_ns = rt.slow_callback_ms * 1_000_000
        while not self._stop.wait(self.interval):
            frames = sys._current_frames()
            loop_frame = frames.get(rt.loop_thread_id)
            if sample and loop_frame is not None:
                self.samples += 1
                if rt.metrics is not None:
                    rt.metrics.profile_samples_total += 1
                key = fold_stack(loop_frame)
                if key in self.stacks or len(self.stacks) < _MAX_STACKS:
                    self.stacks[key] = self.stacks.get(key, 0) + 1
                else:
                    self.stacks[_OVERFLOW_KEY] = (
                        self.stacks.get(_OVERFLOW_KEY, 0) + 1)
            beat = rt.beat_ns
            if not slow_ns or not beat:
                continue
            lag_ns = time.monotonic_ns() - beat
            if lag_ns > slow_ns + self.interval * 2e9:
                # loop pinned: capture the offending callback's stack the
                # first time we see this episode, track the worst lag
                if self._stall_beat != beat:
                    self._stall_beat = beat
                    self._stall_stack = (
                        fold_stack(loop_frame) if loop_frame is not None
                        else "<no-frames>")
                    self._stall_max_ns = lag_ns
                elif lag_ns > self._stall_max_ns:
                    self._stall_max_ns = lag_ns
            elif self._stall_beat:
                self._finish_stall()

    def _finish_stall(self) -> None:
        rt = self.runtime
        duration_ms = round(self._stall_max_ns / 1e6, 1)
        entry = {
            "ts": round(time.time(), 3),
            "duration_ms": duration_ms,
            "stack": self._stall_stack,
        }
        self._stall_beat = 0
        self._stall_max_ns = 0
        self.ring.append(entry)
        self.slow_count += 1
        if rt.metrics is not None:
            rt.metrics.profile_slow_callbacks_total += 1
        node = rt.node
        broker = rt.broker
        if broker is not None:
            node = getattr(broker, "trace_node", None) or node
        # structured line: logjson merges the `data` dict into the JSON
        # object, so the stack is machine-joinable against /admin/profile
        log.warning(
            "slow event-loop callback: %.1f ms", duration_ms,
            extra={"data": {"node": node, "duration_ms": duration_ms,
                            "stack": self._stall_stack}})
        bus = events.ACTIVE
        if bus is not None:
            # sampler thread -> loop thread: the bus publishes AMQP
            # messages, which only the owning loop may do
            bus.emit_threadsafe("profile.slow-callback", {
                "duration_ms": duration_ms, "stack": entry["stack"],
            })

    def collapsed(self) -> str:
        rows = sorted(self.stacks.items(), key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {count}" for stack, count in rows)
