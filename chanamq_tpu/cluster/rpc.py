"""Host-to-host RPC over TCP or Unix-domain sockets.

The DCN control-plane analogue of the reference's Akka artery remoting
(chana-mq-base reference.conf:16-23; messaging pattern SURVEY.md §5:
request/response `ask` with timeout + fire-and-forget `tell`). Wire format
reuses the framework's own AMQP field-table codec for payloads (tables carry
nested tables, byte arrays, ints — everything entity ops need), so the
cluster layer introduces no second serialization scheme and no pickle.

Where a peer lives is abstracted behind a small :class:`Transport` seam:
``TcpTransport`` for inter-node links, ``UdsTransport`` for the intra-node
shard fast path (chanamq_tpu/shard/). Both planes share one codec, flush,
and credit implementation; only the dial differs. Per-peer state keys on
(peer, transport.kind) so a UDS peer never collides with a TCP peer in
counters or backoff bookkeeping.

Frame: u32 body-length | u64 correlation-id | u8 kind | shortstr method |
       table payload
kinds: 0=request 1=response 2=error 3=event (fire-and-forget)

Data-plane frames (cluster/dataplane.py) share the listener but skip the
field-table codec entirely — after the common head comes a u8 method id and
a method-specific binary payload whose bulk fields (message bodies, property
headers) are length-prefixed raw bytes, decoded as memoryview slices of the
read buffer (no copy):

       u32 body-length | u64 correlation-id | u8 kind | u8 method-id | raw
kinds: 4=data-request 5=data-response 6=data-event
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import socket
import struct
from io import BytesIO
from typing import Awaitable, Callable, Optional, Union

from .. import chaos
from ..amqp import value_codec as vc

log = logging.getLogger("chanamq.rpc")

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ERROR = 2
KIND_EVENT = 3
# binary fast-path kinds (cluster/dataplane.py): payload is raw bytes after
# a u8 method id, never a field table
KIND_DREQUEST = 4
KIND_DRESPONSE = 5
KIND_DEVENT = 6

_HEAD = struct.Struct(">IQB")
MAX_FRAME = 64 * 1024 * 1024


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on a TCP interconnect stream: RPC requests and
    data-plane pushes are small framed writes whose latency must not
    ride on the peer's delayed ACK (UDS transports no-op here)."""
    sock = writer.get_extra_info("socket")
    if sock is not None and hasattr(sock, "setsockopt"):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

Handler = Callable[[dict], Awaitable[Optional[dict]]]
# binary handler: memoryview payload -> response payload parts (None = ok)
BinaryHandler = Callable[[memoryview], Awaitable[Optional[list]]]


class RpcError(Exception):
    def __init__(self, code: str, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


class RpcTimeout(RpcError):
    def __init__(self, method: str) -> None:
        super().__init__("timeout", f"rpc {method} timed out")


def _chaos_rpc_error(fault) -> RpcError:
    return RpcError(fault.code, fault.message)


def _encode(corr_id: int, kind: int, method: str, payload: dict) -> bytes:
    body = BytesIO()
    vc.write_shortstr(body, method)
    vc.write_table(body, payload)
    data = body.getvalue()
    return _HEAD.pack(len(data) + 9, corr_id, kind) + data


def encode_data_frame(
    corr_id: int, kind: int, method_id: int, parts: list,
) -> list:
    """Binary frame as a buffer list for writer.writelines: one packed head
    (+ method id) followed by the caller's payload parts verbatim — bodies
    and property blobs are never copied into a joined frame."""
    payload_len = sum(len(p) for p in parts)
    head = bytearray(_HEAD.pack(payload_len + 10, corr_id, kind))
    head.append(method_id)
    return [bytes(head), *parts]


async def _read_frame(
    reader: asyncio.StreamReader,
) -> tuple[int, int, Union[str, int], Union[dict, memoryview]]:
    """One frame off the wire. Table-coded kinds return (corr, kind,
    method-name, payload-dict); data-plane kinds return (corr, kind,
    method-id, payload-memoryview) — the view slices the read buffer, so
    bulk fields inside it are zero-copy all the way to Message.body."""
    head = await reader.readexactly(4)
    (length,) = struct.unpack(">I", head)
    if length > MAX_FRAME:
        # the oversized body is still in the stream: the connection is
        # desynced mid-frame and can only be dropped (callers close the
        # transport and surface a reconnectable error)
        raise FrameTooLarge(f"{length} bytes")
    body = await reader.readexactly(length)
    corr_id, kind = struct.unpack_from(">QB", body)
    if kind >= KIND_DREQUEST:
        view = memoryview(body)
        return corr_id, kind, view[9], view[10:]
    stream = BytesIO(body[9:])
    method = vc.read_shortstr(stream)
    payload = vc.read_table(stream)
    return corr_id, kind, method, payload


class FrameTooLarge(RpcError):
    """A peer announced a frame beyond MAX_FRAME: past this point the byte
    stream cannot be re-synchronized, so the connection must be closed and
    re-established (reconnectable, not a protocol-level reply)."""

    def __init__(self, detail: str) -> None:
        super().__init__("frame_too_large", detail)


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class Transport:
    """Where a peer lives and how to dial it.

    ``label`` names the endpoint for logs and backoff surfaces; ``peer``
    is the identity the chaos seams match rules against — for a UDS link
    to a sibling shard it carries the peer's CLUSTER name, so a fault rule
    scoped to a node fires regardless of which transport reaches it."""

    __slots__ = ()
    kind: str = "tcp"

    @property
    def label(self) -> str:  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def peer(self) -> str:
        return self.label

    async def dial(self):  # pragma: no cover - abstract
        raise NotImplementedError


class TcpTransport(Transport):
    __slots__ = ("host", "port")
    kind = "tcp"

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = int(port)

    @property
    def label(self) -> str:
        return f"{self.host}:{self.port}"

    async def dial(self):
        reader, writer = await asyncio.open_connection(self.host, self.port)
        _set_nodelay(writer)
        return reader, writer

    def __repr__(self) -> str:
        return f"TcpTransport({self.label})"


class UdsTransport(Transport):
    """Unix-domain socket to a process on this machine (a sibling shard):
    same frames, same micro-batching, no TCP stack in the path."""

    __slots__ = ("path", "_peer")
    kind = "uds"

    def __init__(self, path: str, peer: Optional[str] = None) -> None:
        self.path = path
        self._peer = peer

    @property
    def label(self) -> str:
        return f"uds:{self.path}"

    @property
    def peer(self) -> str:
        return self._peer or self.label

    async def dial(self):
        opener = getattr(asyncio, "open_unix_connection", None)
        if opener is None:  # non-unix platform
            raise RpcError("unsupported", "unix sockets unavailable")
        return await opener(self.path)

    def __repr__(self) -> str:
        return f"UdsTransport({self.path})"


def as_transport(host, port: int = 0) -> Transport:
    """Back-compat shim: callers hand either a Transport or (host, port)."""
    return host if isinstance(host, Transport) else TcpTransport(host, port)


class RpcServer:
    """Listens for peer connections; dispatches requests to handlers.

    Besides the TCP endpoint an optional Unix-domain listener (``uds_path``)
    serves the same handlers over the same frames — the intra-node shard
    fast path dials it instead of looping through TCP."""

    def __init__(
        self, host: str, port: int, *, uds_path: Optional[str] = None,
    ) -> None:
        self.host = host
        self.port = port
        self.uds_path = uds_path
        self.handlers: dict[str, Handler] = {}
        self.binary_handlers: dict[int, BinaryHandler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._uds_server: Optional[asyncio.AbstractServer] = None
        self._peer_writers: set[asyncio.StreamWriter] = set()

    def register(self, method: str, handler: Handler) -> None:
        self.handlers[method] = handler

    def register_binary(self, method_id: int, handler: BinaryHandler) -> None:
        """Data-plane handler: receives the raw payload view; its return
        (a buffer list, or None for a bare ok) rides a KIND_DRESPONSE."""
        self.binary_handlers[method_id] = handler

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        if self.uds_path:
            starter = getattr(asyncio, "start_unix_server", None)
            if starter is None:  # non-unix platform: TCP only
                log.warning("unix sockets unavailable; skipping %s",
                            self.uds_path)
                self.uds_path = None
            else:
                # a stale socket file from a crashed predecessor blocks the
                # bind; the supervisor guarantees single ownership per path
                try:
                    os.unlink(self.uds_path)
                except FileNotFoundError:
                    pass
                self._uds_server = await starter(
                    self._on_client, path=self.uds_path)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        servers = [s for s in (self._server, self._uds_server) if s is not None]
        self._server = self._uds_server = None
        if servers:
            for server in servers:
                server.close()
            # close accepted connections first: py3.12 wait_closed() blocks
            # until every connection handler finishes
            for writer in list(self._peer_writers):
                try:
                    writer.close()
                except Exception:
                    pass
            for server in servers:
                await server.wait_closed()
        if self.uds_path:
            try:
                os.unlink(self.uds_path)
            except OSError:
                pass

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        _set_nodelay(writer)
        self._peer_writers.add(writer)
        try:
            while True:
                corr_id, kind, method, payload = await _read_frame(reader)
                if kind == KIND_EVENT:
                    handler = self.handlers.get(method)
                    if handler is not None:
                        # events are fire-and-forget; run concurrently
                        asyncio.get_event_loop().create_task(
                            self._run_event(handler, method, payload))
                    continue
                if kind == KIND_DEVENT:
                    bhandler = self.binary_handlers.get(method)
                    if bhandler is not None:
                        asyncio.get_event_loop().create_task(
                            self._run_binary_event(bhandler, method, payload))
                    continue
                if kind == KIND_DREQUEST:
                    asyncio.get_event_loop().create_task(
                        self._run_binary_request(
                            writer, corr_id, method, payload))
                    continue
                if kind != KIND_REQUEST:
                    continue
                asyncio.get_event_loop().create_task(
                    self._run_request(writer, corr_id, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except FrameTooLarge as exc:
            # desynced mid-stream: drop the connection (the peer's client
            # reconnects); replying in-band is impossible past this point
            log.warning("rpc server closing desynced peer connection: %s", exc)
        except Exception:
            log.exception("rpc server connection failed")
        finally:
            self._peer_writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _run_event(self, handler: Handler, method: str, payload: dict) -> None:
        try:
            await handler(payload)
        except Exception:
            log.exception("rpc event handler %s failed", method)

    async def _run_request(
        self, writer: asyncio.StreamWriter, corr_id: int, method: str, payload: dict
    ) -> None:
        handler = self.handlers.get(method)
        try:
            if handler is None:
                raise RpcError("no_such_method", method)
            result = await handler(payload)
            frame = _encode(corr_id, KIND_RESPONSE, method, result or {})
        except RpcError as exc:
            frame = _encode(corr_id, KIND_ERROR, method,
                            {"code": exc.code, "message": exc.message})
        except Exception as exc:
            log.exception("rpc handler %s failed", method)
            frame = _encode(corr_id, KIND_ERROR, method,
                            {"code": "internal", "message": str(exc)})
        try:
            writer.write(frame)
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def _run_binary_event(
        self, handler: BinaryHandler, method_id: int, payload: memoryview
    ) -> None:
        try:
            await handler(payload)
        except Exception:
            log.exception("rpc binary event handler %d failed", method_id)

    async def _run_binary_request(
        self, writer: asyncio.StreamWriter, corr_id: int, method_id: int,
        payload: memoryview,
    ) -> None:
        """Serve one data-plane request; the reply is a status byte (0=ok)
        plus any handler payload parts, or 1 + shortstr error text."""
        handler = self.binary_handlers.get(method_id)
        try:
            if handler is None:
                raise RpcError("no_such_method", f"binary method {method_id}")
            result = await handler(payload)
            parts = [b"\x00", *(result or [])]
        except Exception as exc:
            if not isinstance(exc, RpcError):
                log.exception("rpc binary handler %d failed", method_id)
            text = str(exc).encode("utf-8", "replace")[:255]
            parts = [b"\x01", bytes((len(text),)), text]
        try:
            writer.writelines(
                encode_data_frame(corr_id, KIND_DRESPONSE, method_id, parts))
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass


class ReconnectBackoff:
    """Backoff shared by the control and data clients: after a failed
    connect, further attempts fail IMMEDIATELY until the deadline so a
    dead peer costs callers one fast exception, not a connect timeout
    each (satellite of the stacked interconnect PR). Success resets it.

    Delay growth is decorrelated jitter — next = uniform(base, prev*3),
    capped at max_s — so N clients dropped by the same peer failure spread
    their reconnects instead of retrying in lockstep. When a seeded chaos
    plan is active the draw comes from the plan's RNG, keeping chaos runs
    reproducible.

    A successful dial only clears the retry deadline; the accumulated
    delay survives until the peer has answered `clean_reset_calls`
    consecutive calls. A flapping peer that accepts connects and then
    drops them used to reset the delay to zero on every dial, turning
    backoff into a tight reconnect loop."""

    __slots__ = ("base_s", "max_s", "failures", "clean_reset_calls",
                 "_delay_s", "_retry_at", "_clean_calls")

    def __init__(
        self, base_s: float = 0.1, max_s: float = 5.0,
        clean_reset_calls: int = 8,
    ) -> None:
        self.base_s = base_s
        self.max_s = max_s
        self.clean_reset_calls = clean_reset_calls
        self.failures = 0  # consecutive failed connects since last success
        self._delay_s = 0.0
        self._retry_at = 0.0
        self._clean_calls = 0  # completed calls since the last failure

    def check(self) -> None:
        if self._delay_s and asyncio.get_event_loop().time() < self._retry_at:
            raise RpcError(
                "backoff", f"reconnect suppressed for {self._delay_s:.1f}s")

    def failed(self) -> None:
        prev = self._delay_s if self._delay_s else self.base_s
        rng = chaos.backoff_rng() or random
        self._delay_s = min(
            self.max_s,
            rng.uniform(self.base_s, max(self.base_s, prev * 3)))
        self.failures += 1
        self._clean_calls = 0
        self._retry_at = asyncio.get_event_loop().time() + self._delay_s

    def succeeded(self) -> None:
        # dial success is not proven health: keep the delay armed so a
        # peer that accepts and immediately drops still backs off
        self._retry_at = 0.0

    def note_clean(self) -> None:
        """A call round-tripped; after enough of them, forgive history."""
        if not self.failures and not self._delay_s:
            return
        self._clean_calls += 1
        if self._clean_calls >= self.clean_reset_calls:
            self._delay_s = 0.0
            self.failures = 0
            self._clean_calls = 0

    def state(self) -> dict:
        """Current backoff posture, surfaced by /admin/cluster."""
        return {
            "delay_s": round(self._delay_s, 4),
            "consecutive_failures": self.failures,
        }


class RpcClient:
    """One outgoing connection to a peer, with correlation-id matching.
    Reconnects lazily on next call after a drop, with exponential backoff
    after a failed connect (a dead peer fails callers fast instead of
    stalling each for the full ask window)."""

    def __init__(
        self, host, port: int = 0, *, timeout_s: float = 20.0,
        connect_timeout_s: float = 3.0,
    ) -> None:
        # host may be a Transport (UDS shard fast path) or a plain host
        # string with a port (the historical TCP signature)
        self.transport = as_transport(host, port)
        self.host = getattr(self.transport, "host", self.transport.label)
        self.port = getattr(self.transport, "port", 0)
        # default ask window (the reference's 20 s internal ask timeout);
        # every call() may override it per request
        self.timeout_s = timeout_s
        self.connect_timeout_s = connect_timeout_s
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._waiters: dict[int, asyncio.Future] = {}
        self._next_corr = 1
        self._connect_lock = asyncio.Lock()
        self._backoff = ReconnectBackoff()
        self.last_error: Optional[str] = None
        self.closed = False

    def backoff_state(self) -> dict:
        state = self._backoff.state()
        state["last_error"] = self.last_error
        return state

    async def _ensure_connected(self) -> asyncio.StreamWriter:
        if self._writer is not None and not self._writer.is_closing():
            return self._writer
        # outside the lock too: callers queued BEHIND a reconnect attempt
        # fail fast once the holder's attempt has failed, instead of each
        # retrying the dial serially
        self._backoff.check()
        async with self._connect_lock:
            if self._writer is not None and not self._writer.is_closing():
                return self._writer
            self._backoff.check()
            try:
                if chaos.ACTIVE is not None:
                    fault = await chaos.ACTIVE.fire(
                        "rpc.connect", peer=self.transport.peer,
                        on_error=_chaos_rpc_error)
                    if fault is not None:
                        raise RpcError(fault.code, fault.message)
                reader, writer = await asyncio.wait_for(
                    self.transport.dial(), self.connect_timeout_s)
            except BaseException as exc:
                self._backoff.failed()
                self.last_error = repr(exc)
                # requests already queued on the lock see the fresh backoff
                raise
            self._backoff.succeeded()
            self._writer = writer
            self._reader_task = asyncio.get_event_loop().create_task(
                self._read_loop(reader, writer))
            return writer

    async def _read_loop(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                corr_id, kind, _method, payload = await _read_frame(reader)
                if chaos.ACTIVE is not None:
                    fault = chaos.ACTIVE.decide(
                        "rpc.read", peer=self.transport.peer)
                    if fault is not None:
                        if fault.kind == "latency":
                            await asyncio.sleep(fault.delay_s)
                        elif fault.kind == "drop":
                            continue  # frame lost in flight
                        elif fault.kind in ("disconnect", "partition"):
                            break  # transport dies; finally reconnects
                        else:  # error / corrupt: stream desync
                            raise FrameTooLarge(
                                f"chaos[{fault.rule}]: {fault.message}")
                fut = self._waiters.pop(corr_id, None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESPONSE:
                    fut.set_result(payload)
                elif kind == KIND_ERROR:
                    fut.set_exception(RpcError(
                        str(payload.get("code", "unknown")),
                        str(payload.get("message", ""))))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError) as exc:
            self.last_error = repr(exc)
        except FrameTooLarge as exc:
            # mid-stream desync: close the transport (finally below) so the
            # next call reconnects cleanly; in-flight waiters fail with a
            # reconnectable error rather than the loop dying unobserved
            log.warning("rpc client %s desynced: %s; reconnecting",
                        self.transport.label, exc)
            self.last_error = repr(exc)
        finally:
            self._fail_waiters(
                RpcError("disconnected", self.transport.label))
            # close OUR writer (dead peer), not whatever reconnect may have
            # installed since; abandoning it would leak the socket until GC
            if self._writer is writer:
                self._writer = None
            try:
                writer.close()
            except Exception:
                pass

    def _fail_waiters(self, exc: Exception) -> None:
        for fut in self._waiters.values():
            if not fut.done():
                fut.set_exception(exc)
                # a cancelled/timed-out call may never await this waiter:
                # mark the exception retrieved so teardown stays silent
                fut.exception()
        self._waiters.clear()

    async def call(
        self, method: str, payload: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> dict:
        writer = await self._ensure_connected()
        if chaos.ACTIVE is not None:
            fault = await chaos.ACTIVE.fire(
                "rpc.call", peer=self.transport.peer,
                on_error=_chaos_rpc_error)
            if fault is not None:
                if fault.kind == "drop":
                    # request lost in flight: surface the timeout now
                    # instead of making the soak wait out the ask window
                    raise RpcTimeout(method)
                writer.close()  # disconnect / corrupt: kill the transport
                raise RpcError("disconnected", f"chaos[{fault.rule}]")
        corr_id = self._next_corr
        self._next_corr += 1
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters[corr_id] = fut
        writer.write(_encode(corr_id, KIND_REQUEST, method, payload or {}))
        await writer.drain()
        try:
            result = await asyncio.wait_for(fut, timeout_s or self.timeout_s)
        except asyncio.TimeoutError:
            self._waiters.pop(corr_id, None)
            raise RpcTimeout(method) from None
        self._backoff.note_clean()
        return result

    async def send_event(self, method: str, payload: Optional[dict] = None) -> None:
        """Fire-and-forget (the reference's `tell`)."""
        writer = await self._ensure_connected()
        if chaos.ACTIVE is not None:
            fault = await chaos.ACTIVE.fire(
                "rpc.event", peer=self.transport.peer,
                on_error=_chaos_rpc_error)
            if fault is not None:
                return  # fire-and-forget: any transport fault = silent loss
        writer.write(_encode(0, KIND_EVENT, method, payload or {}))
        await writer.drain()
        self._backoff.note_clean()

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
            self._writer = None
        self._fail_waiters(RpcError("closed", "client closed"))
