"""Deterministic fault injection (see plan.py for the fault model).

The broker's seams import this module once and gate on ``chaos.ACTIVE``:

    from .. import chaos
    ...
    if chaos.ACTIVE is not None:
        await chaos.ACTIVE.fire("rpc.call", peer=self._peer)

With chaos disabled (the default) ``ACTIVE`` stays ``None`` and every
seam costs a module-attribute load plus an is-None check — no allocation,
no call, no awaits. ``install``/``clear`` swap the hook at runtime (the
/admin/chaos endpoint uses them); ``enable_from_config`` is the boot-time
wiring that also swaps the broker's store for the injecting wrapper.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from .plan import Fault, FaultPlan, FaultRule
from .runtime import ChaosRuntime
from .store import ChaosStore

__all__ = [
    "ACTIVE", "Fault", "FaultPlan", "FaultRule", "ChaosRuntime",
    "ChaosStore", "install", "clear", "backoff_rng", "enable_from_config",
]

log = logging.getLogger("chanamq.chaos")

# THE hook. None = chaos off = seams are no-ops.
ACTIVE: Optional[ChaosRuntime] = None


def install(plan: FaultPlan, metrics=None) -> ChaosRuntime:
    """Activate ``plan``; returns the runtime (also visible as ACTIVE)."""
    global ACTIVE
    ACTIVE = ChaosRuntime(plan, metrics=metrics)
    log.info("chaos plan installed: seed=%d rules=%s fingerprint=%s",
             plan.seed, [r.name for r in plan.rules],
             plan.fingerprint()[:16])
    return ACTIVE


def clear() -> None:
    global ACTIVE
    if ACTIVE is not None:
        log.info("chaos plan cleared after %d fires", ACTIVE.plan.total_fires)
    ACTIVE = None


def backoff_rng():
    """Seeded RNG for reconnect jitter while chaos is active, else None
    (callers fall back to the module-level ``random``)."""
    runtime = ACTIVE
    return runtime.aux_rng() if runtime is not None else None


def enable_from_config(config, broker) -> bool:
    """Boot-time wiring, called from ``run_node`` before traffic starts.

    When ``chana.mq.chaos.enabled`` is set: mark the broker chaos-capable
    (gates /admin/chaos/install), wrap its store so store sites inject,
    and — if ``chana.mq.chaos.plan`` names a JSON plan file — install that
    plan seeded by ``chana.mq.chaos.seed`` (plan file seed wins if both).
    Returns True when chaos was enabled.
    """
    if not config.bool("chana.mq.chaos.enabled"):
        return False
    broker.chaos_enabled = True
    plan_path = config.get("chana.mq.chaos.plan")
    if plan_path:
        with open(plan_path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        data.setdefault("seed", config.int("chana.mq.chaos.seed"))
        install(FaultPlan.from_dict(data), metrics=broker.metrics)
    # wrap through the lazy shim (not ACTIVE directly) so the store keeps
    # injecting across admin-driven install()/clear() cycles
    broker.store = ChaosStore(broker.store, _LazyRuntime())
    return True


class _LazyRuntime:
    """Delegates to whatever runtime is ACTIVE at call time, so a
    ChaosStore built at boot keeps working across install()/clear()."""

    def decide(self, site: str, peer: str = ""):
        runtime = ACTIVE
        return None if runtime is None else runtime.decide(site, peer)

    async def fire(self, site: str, peer: str = "", on_error=None):
        runtime = ACTIVE
        if runtime is None:
            return None
        return await runtime.fire(site, peer, on_error)
