"""Asyncio AMQP 0-9-1 client.

A full protocol client over the same wire codec the server uses (the codec is
shared; the protocol logic — RPC matching, consumer delivery routing, confirm
tracking — is independent). Mirrors the client capability the reference got
from the RabbitMQ Java client plus its own ClientSettings
(chana-mq-base Settings.scala:200-219).
"""

from __future__ import annotations

import asyncio
import socket as socket_module
import ssl as ssl_module
import struct
from collections import deque
from dataclasses import dataclass
from io import BytesIO
from typing import Any, Awaitable, Callable, Optional, Union

from ..amqp.command import AMQCommand, CommandAssembler
from ..amqp.constants import FRAME_OVERHEAD, FrameType, PROTOCOL_HEADER
from ..amqp.frame import Frame, FrameError, FrameParser, HEARTBEAT_BYTES
from ..amqp import methods as am
from ..amqp.properties import BasicProperties

_FRAME_HDR = struct.Struct(">BHI").pack


_DELIVER_CTAG_CACHE: dict[bytes, str] = {}
_DELIVER_EXRK_CACHE: dict[bytes, tuple[str, str]] = {}
# high-cardinality routing keys (per-message unique, e.g. correlation-id
# routing) would turn the exrk cache into pure per-message overhead: after
# repeated churn-driven clears the cache disables itself for the process
_EXRK_CACHE_STRIKES = 4
_exrk_strikes = 0


def _parse_deliver_fields(payload: bytes) -> tuple[str, int, bool, str, str]:
    """Hand-parse a basic.deliver method payload (past the 4 id bytes).

    A consumer's tag and a flow's exchange/routing-key repeat on every
    delivery, so their str decodes are cached keyed by the raw byte slices
    (prefix: ids + consumer-tag; suffix: exchange + routing-key) — a steady
    stream pays two dict hits instead of three utf-8 decodes per message."""
    global _exrk_strikes
    n = payload[4]
    split = 5 + n
    prefix = payload[:split]
    ctag = _DELIVER_CTAG_CACHE.get(prefix)
    if ctag is None:
        if len(_DELIVER_CTAG_CACHE) >= 1024:
            _DELIVER_CTAG_CACHE.clear()
        ctag = _DELIVER_CTAG_CACHE[prefix] = payload[5:split].decode("utf-8")
    delivery_tag = int.from_bytes(payload[split:split + 8], "big")
    redelivered = bool(payload[split + 8] & 1)
    exrk = None
    if _exrk_strikes < _EXRK_CACHE_STRIKES:
        suffix = payload[split + 9:]
        exrk = _DELIVER_EXRK_CACHE.get(suffix)
    if exrk is None:
        pos = split + 9
        n2 = payload[pos]
        exchange = payload[pos + 1:pos + 1 + n2].decode("utf-8")
        pos += 1 + n2
        n2 = payload[pos]
        routing_key = payload[pos + 1:pos + 1 + n2].decode("utf-8")
        exrk = (exchange, routing_key)
        if _exrk_strikes < _EXRK_CACHE_STRIKES:
            if len(_DELIVER_EXRK_CACHE) >= 1024:
                _DELIVER_EXRK_CACHE.clear()
                _exrk_strikes += 1
            _DELIVER_EXRK_CACHE[suffix] = exrk
    return ctag, delivery_tag, redelivered, exrk[0], exrk[1]


class AMQPClientError(Exception):
    pass


class ChannelClosedError(AMQPClientError):
    def __init__(self, reply_code: int, reply_text: str) -> None:
        super().__init__(f"channel closed: {reply_code} {reply_text}")
        self.reply_code = reply_code
        self.reply_text = reply_text


class ConnectionClosedError(AMQPClientError):
    def __init__(self, reply_code: int = 0, reply_text: str = "") -> None:
        super().__init__(f"connection closed: {reply_code} {reply_text}")
        self.reply_code = reply_code
        self.reply_text = reply_text


class DeliveredMessage:
    """One delivered (or got) message. `properties` decodes lazily from the
    raw content-header payload: the consume hot loop never pays the full
    BasicProperties parse for callbacks that only read the body."""

    __slots__ = ("consumer_tag", "delivery_tag", "redelivered", "exchange",
                 "routing_key", "body", "message_count",
                 "_properties", "_header_raw")

    def __init__(
        self, consumer_tag: str, delivery_tag: int, redelivered: bool,
        exchange: str, routing_key: str, body: bytes,
        properties: Optional[BasicProperties] = None,
        header_raw: Optional[bytes] = None,
        message_count: Optional[int] = None,  # set for basic.get replies
    ) -> None:
        self.consumer_tag = consumer_tag
        self.delivery_tag = delivery_tag
        self.redelivered = redelivered
        self.exchange = exchange
        self.routing_key = routing_key
        self.body = body
        self.message_count = message_count
        self._properties = properties
        self._header_raw = header_raw

    @property
    def properties(self) -> BasicProperties:
        if self._properties is None:
            if self._header_raw is not None:
                _, _, self._properties = BasicProperties.decode_header(
                    self._header_raw)
            else:
                self._properties = BasicProperties()
        return self._properties

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, DeliveredMessage):
            return NotImplemented
        return (
            self.consumer_tag == other.consumer_tag
            and self.delivery_tag == other.delivery_tag
            and self.redelivered == other.redelivered
            and self.exchange == other.exchange
            and self.routing_key == other.routing_key
            and self.properties == other.properties
            and self.body == other.body
            and self.message_count == other.message_count
        )

    def __repr__(self) -> str:
        return (
            f"DeliveredMessage(consumer_tag={self.consumer_tag!r}, "
            f"delivery_tag={self.delivery_tag}, "
            f"redelivered={self.redelivered}, exchange={self.exchange!r}, "
            f"routing_key={self.routing_key!r}, "
            f"properties={self.properties!r}, body={self.body!r}, "
            f"message_count={self.message_count})"
        )


@dataclass(slots=True)
class ReturnedMessage:
    reply_code: int
    reply_text: str
    exchange: str
    routing_key: str
    properties: BasicProperties
    body: bytes


ConsumerCallback = Callable[[DeliveredMessage], Union[None, Awaitable[None]]]


class AMQPClient:
    """One client connection. Use `await AMQPClient.connect(...)`."""

    def __init__(self) -> None:
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        from .. import native_ext

        if native_ext.available():
            self._parser: FrameParser = native_ext.NativeFrameParser()  # type: ignore[assignment]
        else:
            self._parser = FrameParser()
        self._assembler = CommandAssembler()
        # outbound coalescing: sends buffer here and flush once per loop
        # tick (one syscall per batch instead of per method/publish)
        self._wparts: list[bytes] = []
        self._wflush_scheduled = False
        self.channels: dict[int, "ClientChannel"] = {}
        self._next_channel = 1
        self._free_channel_ids: list[int] = []
        self.frame_max = 131072
        self.channel_max = 2047
        self.heartbeat_s = 0
        self.server_properties: dict[str, Any] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._conn_waiters: list[tuple[tuple[type, ...], asyncio.Future]] = []
        self.closed = False
        self._close_exc: Optional[Exception] = None
        # last Connection.Blocked/Unblocked notification from the server
        self.server_blocked = False

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    async def connect(
        cls,
        host: str = "127.0.0.1",
        port: int = 5672,
        *,
        vhost: str = "/",
        username: str = "guest",
        password: str = "guest",
        heartbeat: Optional[int] = None,  # None: accept server's; 0: disable
        ssl: Optional[ssl_module.SSLContext] = None,
        client_properties: Optional[dict] = None,
    ) -> "AMQPClient":
        self = cls()
        self.reader, self.writer = await asyncio.open_connection(host, port, ssl=ssl)
        sock = self.writer.get_extra_info("socket")
        if sock is not None and hasattr(sock, "setsockopt"):
            try:
                # small publish/ack writes must not wait on Nagle
                sock.setsockopt(
                    socket_module.IPPROTO_TCP, socket_module.TCP_NODELAY, 1)
            except OSError:
                pass
        self.writer.write(PROTOCOL_HEADER)
        await self.writer.drain()
        self._reader_task = asyncio.create_task(self._read_loop())

        start = await self._wait_connection_method((am.Connection.Start,))
        self.server_properties = start.server_properties
        mechanisms = bytes(start.mechanisms).split()
        mech = b"PLAIN" if b"PLAIN" in mechanisms else mechanisms[0]
        response = b"\x00" + username.encode() + b"\x00" + password.encode() \
            if mech == b"PLAIN" else b""
        self._send_method(0, am.Connection.StartOk(
            client_properties=client_properties or {
                "product": "chanamq-tpu-client",
                # opt in to Connection.Blocked/Unblocked notifications
                "capabilities": {"connection.blocked": True,
                                 "consumer_cancel_notify": True},
            },
            mechanism=mech.decode(), response=response, locale="en_US",
        ))
        tune = await self._wait_connection_method((am.Connection.Tune,))
        self.channel_max = tune.channel_max or 2047
        self.frame_max = tune.frame_max or 131072
        self._parser.frame_max = self.frame_max
        self.heartbeat_s = tune.heartbeat if heartbeat is None else heartbeat
        self._send_method(0, am.Connection.TuneOk(
            channel_max=self.channel_max, frame_max=self.frame_max,
            heartbeat=self.heartbeat_s,
        ))
        self._send_method(0, am.Connection.Open(virtual_host=vhost))
        await self._wait_connection_method((am.Connection.OpenOk,))
        if self.heartbeat_s:
            self._heartbeat_task = asyncio.create_task(self._heartbeat_loop())
        return self

    async def close(self) -> None:
        if self.closed or self.writer is None:
            return
        try:
            self._send_method(0, am.Connection.Close(reply_code=200, reply_text="bye"))
            await self._wait_connection_method((am.Connection.CloseOk,), timeout=2)
        except Exception:
            pass
        await self._shutdown(None)

    async def _shutdown(self, exc: Optional[Exception]) -> None:
        if self.closed:
            return
        self._flush_writes()  # e.g. a pending CloseOk reply
        self.closed = True
        self._close_exc = exc
        if self._heartbeat_task:
            self._heartbeat_task.cancel()
        for channel in list(self.channels.values()):
            channel._connection_lost(exc)
        self.channels.clear()
        for _, fut in self._conn_waiters:
            if not fut.done():
                if exc:
                    fut.set_exception(exc)
                else:
                    fut.set_exception(ConnectionClosedError())
        self._conn_waiters.clear()
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:
                pass
        if self._reader_task and asyncio.current_task() is not self._reader_task:
            self._reader_task.cancel()

    # -- channels ----------------------------------------------------------

    async def channel(self) -> "ClientChannel":
        if self.closed:
            raise self._close_exc or ConnectionClosedError()
        if self._free_channel_ids:
            cid = self._free_channel_ids.pop()
        else:
            if self._next_channel > self.channel_max:
                raise AMQPClientError(
                    f"out of channel ids (channel_max={self.channel_max})")
            cid = self._next_channel
            self._next_channel += 1
        channel = ClientChannel(self, cid)
        self.channels[cid] = channel
        self._send_method(cid, am.Channel.Open())
        await channel._wait((am.Channel.OpenOk,))
        return channel

    # -- wire I/O ----------------------------------------------------------

    def _write(self, data: bytes) -> None:
        """Buffer outbound bytes; flushed once per event-loop tick."""
        self._wparts.append(data)
        if not self._wflush_scheduled:
            self._wflush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush_writes)

    def _flush_writes(self) -> None:
        self._wflush_scheduled = False
        if self._wparts and self.writer is not None and not self.closed:
            data = b"".join(self._wparts)
            self._wparts.clear()
            try:
                self.writer.write(data)
            except Exception:
                pass  # reader loop surfaces the connection error

    async def drain(self) -> None:
        """Flush the coalescing buffer and wait for the transport."""
        self._flush_writes()
        if self.writer is not None:
            await self.writer.drain()

    def _send_method(self, channel: int, method: am.Method) -> None:
        self._write(Frame.method(channel, method.encode()).to_bytes())

    def _send_command(self, command: AMQCommand) -> None:
        self._write(command.render(self.frame_max))

    async def _read_loop(self) -> None:
        assert self.reader is not None
        # fast-path state for in-flight basic.deliver content, per channel:
        # [fields_tuple, props, body_size, chunks, received]
        fast_partial: dict[int, list] = {}
        scan = getattr(self._parser, "scan_batches", None)
        try:
            while True:
                data = await self.reader.read(262144)
                if not data:
                    await self._shutdown(ConnectionClosedError(0, "server closed"))
                    return
                if scan is not None:
                    if not await self._consume_scan(scan(data), fast_partial):
                        return
                else:
                    for item in self._parser.feed(data):
                        if isinstance(item, FrameError):
                            await self._shutdown(
                                ConnectionClosedError(int(item.code), item.message))
                            return
                        if not await self._handle_frame(
                                item.type, item.channel, item.payload,
                                fast_partial):
                            return
        except asyncio.CancelledError:
            pass
        except Exception as exc:
            await self._shutdown(exc)

    async def _consume_scan(self, batches, fast_partial: dict) -> bool:
        """Native-parser read loop: walk the scan arrays directly. A
        contained basic.deliver (method+header+body frames in one batch)
        is handled inline with no Frame objects at all; everything else
        (cross-batch content, other methods) drops to _handle_frame."""
        for batch in batches:
            if isinstance(batch, FrameError):
                await self._shutdown(
                    ConnectionClosedError(int(batch.code), batch.message))
                return False
            raw, n, types, channels, offsets, lengths = batch[:6]
            i = 0
            while i < n:
                ftype = types[i]
                if ftype == 8:  # heartbeat
                    i += 1
                    continue
                cid = channels[i]
                off = offsets[i]
                if (ftype == 1 and cid not in fast_partial
                        and raw[off:off + 4] == b"\x00\x3c\x00\x3c"
                        and i + 1 < n and types[i + 1] == 2
                        and channels[i + 1] == cid):
                    hoff = offsets[i + 1]
                    header = raw[hoff:hoff + lengths[i + 1]]
                    if len(header) >= 12:
                        body_size = int.from_bytes(header[4:12], "big")
                        j = i + 2
                        got = 0
                        first = None
                        chunks = None
                        complete = body_size == 0
                        while got < body_size:
                            if j >= n or types[j] != 3 or channels[j] != cid:
                                break  # spans the batch: stateful path
                            boff = offsets[j]
                            blen = lengths[j]
                            got += blen
                            if first is None:
                                first = raw[boff:boff + blen]
                            else:
                                if chunks is None:
                                    chunks = [first]
                                chunks.append(raw[boff:boff + blen])
                            j += 1
                            if got >= body_size:
                                complete = True
                        if complete:
                            if body_size == 0:
                                body = b""
                            else:
                                body = first if chunks is None else b"".join(chunks)
                            fields = _parse_deliver_fields(
                                raw[off:off + lengths[i]])
                            await self._deliver_fast(cid, (fields, header), body)
                            i = max(j, i + 2)
                            continue
                if not await self._handle_frame(
                        ftype, cid, raw[off:off + lengths[i]], fast_partial):
                    return False
                i += 1
        return True

    async def _handle_frame(
        self, ftype: int, cid: int, payload: bytes, fast_partial: dict
    ) -> bool:
        """One frame through the stateful path: the per-channel deliver
        state machine first, then the generic assembler. Returns False when
        the connection has shut down."""
        # -- basic.deliver fast path: per AMQP 0-9-1 §4.2.6 content frames
        # are never interleaved with other frames on the SAME channel, so a
        # tiny inline state machine can own the method->header->body
        # sequence and skip the generic assembler + Method object entirely.
        if ftype == FrameType.METHOD:
            if cid in fast_partial:
                # §4.2.6: content frames are never interleaved with methods
                # on the same channel. Feeding the assembler with fast state
                # still active would silently desynchronize delivery.
                del fast_partial[cid]
                await self._shutdown(ConnectionClosedError(
                    505,
                    "method frame interleaved with in-flight "
                    f"content on channel {cid}"))
                return False
            if payload[:4] == b"\x00\x3c\x00\x3c":
                fast_partial[cid] = [
                    _parse_deliver_fields(payload), None, 0, [], 0]
                return True
        elif cid in fast_partial:
            partial = fast_partial[cid]
            if ftype == FrameType.HEADER:
                # raw header only: properties decode lazily on
                # DeliveredMessage.properties access (hot loop: class 2B +
                # weight 2B, then 8B body size)
                if len(payload) < 12:
                    await self._shutdown(ConnectionClosedError(
                        502, f"truncated content header on channel {cid}"))
                    return False
                body_size = int.from_bytes(payload[4:12], "big")
                partial[1] = payload
                partial[2] = body_size
                if body_size == 0:
                    del fast_partial[cid]
                    await self._deliver_fast(cid, partial, b"")
                return True
            if ftype == FrameType.BODY:
                partial[3].append(payload)
                partial[4] += len(payload)
                if partial[4] >= partial[2]:
                    del fast_partial[cid]
                    chunks = partial[3]
                    body = chunks[0] if len(chunks) == 1 else b"".join(chunks)
                    await self._deliver_fast(cid, partial, body)
                return True
        if ftype == FrameType.HEARTBEAT:
            return True
        out = self._assembler.feed_one(
            Frame(ftype, cid, payload))
        if out is not None:
            if isinstance(out, FrameError):
                await self._shutdown(
                    ConnectionClosedError(int(out.code), out.message))
                return False
            await self._on_command(out)
        return True

    async def _deliver_fast(self, cid: int, partial: list, body: bytes) -> None:
        consumer_tag, delivery_tag, redelivered, exchange, routing_key = partial[0]
        channel = self.channels.get(cid)
        if channel is None:
            return
        msg = DeliveredMessage(
            consumer_tag=consumer_tag, delivery_tag=delivery_tag,
            redelivered=redelivered, exchange=exchange,
            routing_key=routing_key, header_raw=partial[1], body=body,
        )
        callback = channel._consumers.get(consumer_tag)
        if callback is not None:
            result = callback(msg)
            if result is not None and asyncio.iscoroutine(result):
                await result
        else:
            channel._pending_deliveries.setdefault(consumer_tag, []).append(msg)

    async def _on_command(self, command: AMQCommand) -> None:
        method = command.method
        if command.channel == 0:
            if isinstance(method, am.Connection.Close):
                self._send_method(0, am.Connection.CloseOk())
                await self._shutdown(
                    ConnectionClosedError(method.reply_code, method.reply_text))
                return
            if isinstance(method, am.Connection.Blocked):
                self.server_blocked = True
                return
            if isinstance(method, am.Connection.Unblocked):
                self.server_blocked = False
                return
            for i, (types, fut) in enumerate(self._conn_waiters):
                if isinstance(method, types) and not fut.done():
                    self._conn_waiters.pop(i)
                    fut.set_result(method)
                    return
            return
        channel = self.channels.get(command.channel)
        if channel is not None:
            await channel._on_command(command)

    async def _wait_connection_method(
        self, types: tuple[type, ...], timeout: float = 10
    ) -> Any:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._conn_waiters.append((types, fut))
        return await asyncio.wait_for(fut, timeout)

    async def _heartbeat_loop(self) -> None:
        try:
            while not self.closed:
                await asyncio.sleep(max(self.heartbeat_s / 2, 0.5))
                if self.writer is not None:
                    self.writer.write(HEARTBEAT_BYTES)
        except (asyncio.CancelledError, ConnectionResetError):
            pass


class ClientChannel:
    """One channel on a client connection."""

    def __init__(self, client: AMQPClient, channel_id: int) -> None:
        self.client = client
        self.id = channel_id
        self.closed = False
        self.close_reason: Optional[ChannelClosedError] = None
        self._waiters: list[tuple[tuple[type, ...], asyncio.Future]] = []
        self._consumers: dict[str, ConsumerCallback] = {}
        # deliveries racing the consume-ok -> registration gap are buffered
        self._pending_deliveries: dict[str, list[DeliveredMessage]] = {}
        self.returns: list[ReturnedMessage] = []
        # consumer tags the SERVER cancelled (queue died under them)
        self.cancelled_consumers: list[str] = []
        # server-initiated Channel.Flow state (broker overload throttle):
        # False while the broker asked us to stop publishing; flow_events
        # records every transition in order for tests/diagnostics
        self.flow_active = True
        self.flow_events: list[bool] = []
        # confirm mode
        self.confirm_mode = False
        self._publish_seq = 0
        self._confirm_waiters: dict[int, asyncio.Future] = {}
        # in-flight publish seqs, ascending (append at publish, popleft on
        # the broker's coalesced multiple-acks): confirming a prefix is
        # O(confirmed), not O(window) — a set comprehension re-scanning the
        # full in-flight window per ack was measurable at PerfTest windows
        self.unconfirmed: deque[int] = deque()
        self._confirm_event = asyncio.Event()
        # publish template cache: (exchange, routing_key, mandatory,
        # immediate, id(props)) -> (props_ref, props_snapshot, method_frame,
        # props_payload). The snapshot (a copy taken at encode time) is
        # compared against the live object on every hit, so mutating a
        # reused props object between publishes re-encodes instead of
        # silently sending stale bytes; the ref also pins the id against
        # allocator recycling.
        self._publish_cache: dict[tuple, tuple] = {}

    # -- RPC plumbing ------------------------------------------------------

    async def _wait(self, types: tuple[type, ...], timeout: float = 10) -> Any:
        if self.closed:
            raise self.close_reason or ChannelClosedError(0, "closed")
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters.append((types, fut))
        return await asyncio.wait_for(fut, timeout)

    def _send(self, method: am.Method) -> None:
        if self.closed:
            raise self.close_reason or ChannelClosedError(0, "closed")
        self.client._send_method(self.id, method)

    async def _rpc(self, method: am.Method, reply_types: tuple[type, ...]) -> Any:
        self._send(method)
        return await self._wait(reply_types)

    async def _on_command(self, command: AMQCommand) -> None:
        method = command.method
        if isinstance(method, am.Basic.Deliver):
            msg = DeliveredMessage(
                consumer_tag=method.consumer_tag,
                delivery_tag=method.delivery_tag,
                redelivered=method.redelivered,
                exchange=method.exchange,
                routing_key=method.routing_key,
                properties=command.properties or BasicProperties(),
                body=command.body,
            )
            callback = self._consumers.get(method.consumer_tag)
            if callback is not None:
                result = callback(msg)
                if asyncio.iscoroutine(result):
                    await result
            else:
                self._pending_deliveries.setdefault(
                    method.consumer_tag, []).append(msg)
            return
        if isinstance(method, am.Basic.Cancel):
            # server-sent cancel: the queue died under this consumer
            # (consumer_cancel_notify capability)
            self._consumers.pop(method.consumer_tag, None)
            self.cancelled_consumers.append(method.consumer_tag)
            if not method.nowait:
                self.client._send_method(self.id, am.Basic.CancelOk(
                    consumer_tag=method.consumer_tag))
            return
        if isinstance(method, am.Basic.Return):
            self.returns.append(ReturnedMessage(
                reply_code=method.reply_code, reply_text=method.reply_text,
                exchange=method.exchange, routing_key=method.routing_key,
                properties=command.properties or BasicProperties(),
                body=command.body,
            ))
            return
        if isinstance(method, am.Basic.Ack) and self.confirm_mode:
            self._on_confirm(method.delivery_tag, method.multiple, nack=False)
            return
        if isinstance(method, am.Basic.Nack) and self.confirm_mode:
            self._on_confirm(method.delivery_tag, method.multiple, nack=True)
            return
        if isinstance(method, am.Channel.Close):
            self.client._send_method(self.id, am.Channel.CloseOk())
            self._closed_by_server(
                ChannelClosedError(method.reply_code, method.reply_text))
            return
        if isinstance(method, am.Channel.Flow):
            self.flow_active = method.active
            self.flow_events.append(method.active)
            self.client._send_method(self.id, am.Channel.FlowOk(active=method.active))
            return
        if isinstance(method, (am.Basic.GetOk, am.Basic.GetEmpty)):
            for i, (types, fut) in enumerate(self._waiters):
                if isinstance(method, types) and not fut.done():
                    self._waiters.pop(i)
                    if isinstance(method, am.Basic.GetOk):
                        fut.set_result(DeliveredMessage(
                            consumer_tag="",
                            delivery_tag=method.delivery_tag,
                            redelivered=method.redelivered,
                            exchange=method.exchange,
                            routing_key=method.routing_key,
                            properties=command.properties or BasicProperties(),
                            body=command.body,
                            message_count=method.message_count,
                        ))
                    else:
                        fut.set_result(None)
                    return
            return
        for i, (types, fut) in enumerate(self._waiters):
            if isinstance(method, types) and not fut.done():
                self._waiters.pop(i)
                fut.set_result(method)
                return

    def _closed_by_server(self, exc: ChannelClosedError) -> None:
        self.closed = True
        self.close_reason = exc
        self._confirm_event.set()  # wake wait_unconfirmed_below immediately
        if self.client.channels.pop(self.id, None) is not None:
            self.client._free_channel_ids.append(self.id)
        for _, fut in self._waiters:
            if not fut.done():
                fut.set_exception(exc)
        self._waiters.clear()
        for fut in self._confirm_waiters.values():
            if not fut.done():
                fut.set_exception(exc)
        self._confirm_waiters.clear()

    def _connection_lost(self, exc: Optional[Exception]) -> None:
        self._closed_by_server(
            exc if isinstance(exc, ChannelClosedError)
            else ChannelClosedError(0, str(exc) if exc else "connection closed"))

    # -- confirm tracking --------------------------------------------------

    def _on_confirm(self, delivery_tag: int, multiple: bool, nack: bool) -> None:
        unconfirmed = self.unconfirmed
        if multiple:
            tags = []
            while unconfirmed and unconfirmed[0] <= delivery_tag:
                tags.append(unconfirmed.popleft())
        else:
            tags = [delivery_tag]
            try:
                unconfirmed.remove(delivery_tag)  # rare: single ack/nack
            except ValueError:
                pass
        for tag in tags:
            fut = self._confirm_waiters.pop(tag, None)
            if fut is not None and not fut.done():
                if nack:
                    fut.set_exception(AMQPClientError(f"publish {tag} nacked"))
                else:
                    fut.set_result(True)
        self._confirm_event.set()

    async def wait_unconfirmed_below(self, n: int, timeout: float = 30) -> None:
        """Block until fewer than n publishes are awaiting confirmation
        (the PerfTest-style in-flight window)."""
        deadline = asyncio.get_event_loop().time() + timeout
        while len(self.unconfirmed) >= n:
            if self.closed:
                raise self.close_reason or ChannelClosedError(0, "closed")
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise asyncio.TimeoutError(
                    f"still {len(self.unconfirmed)} unconfirmed")
            self._confirm_event.clear()
            try:
                await asyncio.wait_for(self._confirm_event.wait(), remaining)
            except asyncio.TimeoutError:
                continue

    # -- channel ops -------------------------------------------------------

    async def close(self) -> None:
        if self.closed:
            return
        try:
            await self._rpc(
                am.Channel.Close(reply_code=200, reply_text="bye"),
                (am.Channel.CloseOk,))
        finally:
            self.closed = True
            if self.client.channels.pop(self.id, None) is not None:
                self.client._free_channel_ids.append(self.id)

    async def flow(self, active: bool) -> bool:
        ok = await self._rpc(am.Channel.Flow(active=active), (am.Channel.FlowOk,))
        return ok.active

    # -- exchange ops ------------------------------------------------------

    async def exchange_declare(
        self, exchange: str, type: str = "direct", *, passive: bool = False,
        durable: bool = False, auto_delete: bool = False, internal: bool = False,
        arguments: Optional[dict] = None,
    ) -> None:
        await self._rpc(am.Exchange.Declare(
            exchange=exchange, type=type, passive=passive, durable=durable,
            auto_delete=auto_delete, internal=internal, arguments=arguments,
        ), (am.Exchange.DeclareOk,))

    async def exchange_bind(
        self, destination: str, source: str, routing_key: str = "",
        arguments: Optional[dict] = None,
    ) -> None:
        await self._rpc(am.Exchange.Bind(
            destination=destination, source=source, routing_key=routing_key,
            arguments=arguments or {}), (am.Exchange.BindOk,))

    async def exchange_unbind(
        self, destination: str, source: str, routing_key: str = "",
        arguments: Optional[dict] = None,
    ) -> None:
        await self._rpc(am.Exchange.Unbind(
            destination=destination, source=source, routing_key=routing_key,
            arguments=arguments or {}), (am.Exchange.UnbindOk,))

    async def exchange_delete(self, exchange: str, *, if_unused: bool = False) -> None:
        await self._rpc(am.Exchange.Delete(exchange=exchange, if_unused=if_unused),
                        (am.Exchange.DeleteOk,))

    # -- queue ops ---------------------------------------------------------

    async def queue_declare(
        self, queue: str = "", *, passive: bool = False, durable: bool = False,
        exclusive: bool = False, auto_delete: bool = False,
        arguments: Optional[dict] = None,
    ) -> am.Method:
        """Returns DeclareOk (fields: queue, message_count, consumer_count)."""
        return await self._rpc(am.Queue.Declare(
            queue=queue, passive=passive, durable=durable, exclusive=exclusive,
            auto_delete=auto_delete, arguments=arguments,
        ), (am.Queue.DeclareOk,))

    async def queue_bind(
        self, queue: str, exchange: str, routing_key: str = "",
        arguments: Optional[dict] = None,
    ) -> None:
        await self._rpc(am.Queue.Bind(
            queue=queue, exchange=exchange, routing_key=routing_key,
            arguments=arguments,
        ), (am.Queue.BindOk,))

    async def queue_unbind(
        self, queue: str, exchange: str, routing_key: str = "",
        arguments: Optional[dict] = None,
    ) -> None:
        await self._rpc(am.Queue.Unbind(
            queue=queue, exchange=exchange, routing_key=routing_key,
            arguments=arguments,
        ), (am.Queue.UnbindOk,))

    async def queue_purge(self, queue: str) -> int:
        ok = await self._rpc(am.Queue.Purge(queue=queue), (am.Queue.PurgeOk,))
        return ok.message_count

    async def queue_delete(
        self, queue: str, *, if_unused: bool = False, if_empty: bool = False
    ) -> int:
        ok = await self._rpc(am.Queue.Delete(
            queue=queue, if_unused=if_unused, if_empty=if_empty,
        ), (am.Queue.DeleteOk,))
        return ok.message_count

    # -- basic ops ---------------------------------------------------------

    async def basic_qos(
        self, *, prefetch_size: int = 0, prefetch_count: int = 0,
        global_: bool = False,
    ) -> None:
        await self._rpc(am.Basic.Qos(
            prefetch_size=prefetch_size, prefetch_count=prefetch_count,
            global_=global_,
        ), (am.Basic.QosOk,))

    def basic_publish(
        self, body: bytes, *, exchange: str = "", routing_key: str = "",
        properties: Optional[BasicProperties] = None,
        mandatory: bool = False, immediate: bool = False,
    ) -> Optional[int]:
        """Fire-and-forget publish. In confirm mode returns the seq number.

        Hot loop: the method frame and encoded properties are cached per
        (exchange, routing-key, flags, properties object) — republishing
        with the same arguments only re-frames the header (body size varies)
        and the body."""
        if type(body) is not bytes:
            # snapshot mutable buffers (bytearray/memoryview) NOW: the body
            # rides the write buffer by reference until the next loop-tick
            # flush, and a caller-side mutation must not reach the wire
            body = bytes(body)
        key = (exchange, routing_key, mandatory, immediate, id(properties))
        entry = self._publish_cache.get(key)
        if entry is not None and properties is not None \
                and entry[1] != properties:
            entry = None  # props object mutated since it was cached
        if entry is None:
            props = properties or BasicProperties()
            method_payload = am.Basic.Publish(
                exchange=exchange, routing_key=routing_key,
                mandatory=mandatory, immediate=immediate).encode()
            method_frame = (
                _FRAME_HDR(1, self.id, len(method_payload))
                + method_payload + b"\xce")
            props_out = BytesIO()
            props.write_properties(props_out)
            if len(self._publish_cache) >= 256:
                self._publish_cache.clear()
            # entry[4]: body-length -> fully-rendered wire prefix (method
            # frame + header frame + body frame header) — a steady stream
            # of same-shaped publishes is a dict hit + 3 buffer appends
            entry = (properties, props.copy(), method_frame,
                     props_out.getvalue(), {})
            self._publish_cache[key] = entry
        if self.closed:
            raise self.close_reason or ChannelClosedError(0, "closed")
        body_len = len(body)
        size_cache = entry[4]
        prefix = size_cache.get(body_len)
        if prefix is None:
            method_frame, props_payload = entry[2], entry[3]
            cid = self.id
            frame_max = self.client.frame_max
            max_payload = (frame_max - FRAME_OVERHEAD) if frame_max else body_len
            header = (
                _FRAME_HDR(2, cid, 12 + len(props_payload))
                + b"\x00\x3c\x00\x00"  # class 60 (basic), weight 0
                + body_len.to_bytes(8, "big")
                + props_payload + b"\xce")
            if body_len == 0 or body_len <= max_payload:
                prefix = method_frame + header
                if body_len:
                    prefix += _FRAME_HDR(3, cid, body_len)
                if len(size_cache) >= 64:
                    size_cache.clear()
                size_cache[body_len] = prefix
            else:
                # oversized body: fragment without caching (size varies by
                # chunk; the cost is dominated by the copies anyway)
                parts = [method_frame, header]
                for off in range(0, body_len, max_payload):
                    chunk = body[off:off + max_payload]
                    parts += (_FRAME_HDR(3, cid, len(chunk)), chunk, b"\xce")
                self.client._write(b"".join(parts))
                if self.confirm_mode:
                    self._publish_seq += 1
                    self.unconfirmed.append(self._publish_seq)
                    return self._publish_seq
                return None
        client = self.client
        wparts = client._wparts
        if body_len:
            wparts += (prefix, body, b"\xce")
        else:
            wparts.append(prefix)
        if not client._wflush_scheduled:
            client._wflush_scheduled = True
            asyncio.get_event_loop().call_soon(client._flush_writes)
        if self.confirm_mode:
            self._publish_seq += 1
            self.unconfirmed.append(self._publish_seq)
            return self._publish_seq
        return None

    async def basic_publish_confirmed(
        self, body: bytes, *, exchange: str = "", routing_key: str = "",
        properties: Optional[BasicProperties] = None,
        mandatory: bool = False, immediate: bool = False, timeout: float = 10,
    ) -> None:
        """Publish and await the broker confirm (requires confirm_select)."""
        seq = self.basic_publish(
            body, exchange=exchange, routing_key=routing_key,
            properties=properties, mandatory=mandatory, immediate=immediate)
        assert seq is not None, "confirm_select first"
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._confirm_waiters[seq] = fut
        await asyncio.wait_for(fut, timeout)

    async def basic_consume(
        self, queue: str, callback: ConsumerCallback, *,
        consumer_tag: str = "", no_ack: bool = False, exclusive: bool = False,
        arguments: Optional[dict] = None,
    ) -> str:
        ok = await self._rpc(am.Basic.Consume(
            queue=queue, consumer_tag=consumer_tag, no_ack=no_ack,
            exclusive=exclusive, arguments=arguments,
        ), (am.Basic.ConsumeOk,))
        self._consumers[ok.consumer_tag] = callback
        for msg in self._pending_deliveries.pop(ok.consumer_tag, []):
            result = callback(msg)
            if asyncio.iscoroutine(result):
                await result
        return ok.consumer_tag

    async def basic_cancel(self, consumer_tag: str) -> None:
        await self._rpc(am.Basic.Cancel(consumer_tag=consumer_tag),
                        (am.Basic.CancelOk,))
        self._consumers.pop(consumer_tag, None)

    async def basic_get(
        self, queue: str, *, no_ack: bool = False
    ) -> Optional[DeliveredMessage]:
        self._send(am.Basic.Get(queue=queue, no_ack=no_ack))
        return await self._wait((am.Basic.GetOk, am.Basic.GetEmpty))

    def basic_ack(self, delivery_tag: int, *, multiple: bool = False) -> None:
        # hand-assembled 21-byte frame (header + class/method + tag + bit +
        # end): acks run once per consumed message in ack mode
        if self.closed:
            raise self.close_reason or ChannelClosedError(0, "closed")
        self.client._write(
            _FRAME_HDR(1, self.id, 13)
            + b"\x00\x3c\x00\x50"
            + delivery_tag.to_bytes(8, "big")
            + (b"\x01" if multiple else b"\x00")
            + b"\xce")

    def basic_nack(
        self, delivery_tag: int, *, multiple: bool = False, requeue: bool = True
    ) -> None:
        self._send(am.Basic.Nack(
            delivery_tag=delivery_tag, multiple=multiple, requeue=requeue))

    def basic_reject(self, delivery_tag: int, *, requeue: bool = True) -> None:
        self._send(am.Basic.Reject(delivery_tag=delivery_tag, requeue=requeue))

    async def basic_recover(self, *, requeue: bool = True) -> None:
        await self._rpc(am.Basic.Recover(requeue=requeue), (am.Basic.RecoverOk,))

    async def confirm_select(self) -> None:
        await self._rpc(am.Confirm.Select(), (am.Confirm.SelectOk,))
        self.confirm_mode = True

    # -- tx ----------------------------------------------------------------

    async def tx_select(self) -> None:
        await self._rpc(am.Tx.Select(), (am.Tx.SelectOk,))

    async def tx_commit(self) -> None:
        await self._rpc(am.Tx.Commit(), (am.Tx.CommitOk,))

    async def tx_rollback(self) -> None:
        await self._rpc(am.Tx.Rollback(), (am.Tx.RollbackOk,))
