"""Deterministic shard topology: names, ports, and socket paths.

Both sides compute the same layout from the same inputs — the
supervisor from the merged config (then forwards the resolved pieces to
workers via ``CHANAMQ_SHARD_*`` environment variables), each worker
from those variables plus its per-process cluster port:

* shard ``i``'s cluster endpoint is ``host:(base_port + i)`` — member
  names stay ``host:port`` strings, so the hash ring, membership gossip
  and holder metadata need no new name syntax;
* shard ``i``'s RPC/data Unix socket is ``<dir>/shard-i.sock``;
* the fd-handoff feed (reuse-port fallback) is ``<dir>/handoff-i.sock``.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import Iterable, Mapping, Optional


def resolve_count(config) -> int:
    """``chana.mq.shard.count``: 1 = off, 0 = one shard per core."""
    raw = config.int("chana.mq.shard.count")
    if raw <= 0:
        return os.cpu_count() or 1
    return raw


def resolve_dir(config) -> str:
    """The Unix-socket directory; created on demand. An explicit
    ``chana.mq.shard.dir`` wins; otherwise a fresh temp dir (socket
    paths must stay under the ~100-byte sun_path limit, so the store
    directory — often deep — is deliberately not the default)."""
    configured = str(config.get("chana.mq.shard.dir") or "")
    if configured:
        os.makedirs(configured, exist_ok=True)
        return configured
    return tempfile.mkdtemp(prefix="chanamq-shards-")


@dataclass(frozen=True)
class ShardTopology:
    count: int
    host: str
    base_port: int
    dir: str

    @classmethod
    def from_config(cls, config) -> "ShardTopology":
        """Supervisor-side construction from the merged config."""
        return cls(
            count=resolve_count(config),
            host=config.str("chana.mq.cluster.host"),
            base_port=config.int("chana.mq.cluster.port"),
            dir=resolve_dir(config),
        )

    @classmethod
    def from_env(
        cls, config, index: int,
        environ: Optional[Mapping[str, str]] = None,
    ) -> "ShardTopology":
        """Worker-side construction: the supervisor already overrode
        this process's ``chana.mq.cluster.port`` to ``base + index``,
        so the base is recovered by subtraction."""
        env = os.environ if environ is None else environ
        count = int(env.get("CHANAMQ_SHARD_COUNT") or 0) \
            or max(1, config.int("chana.mq.shard.count"))
        sdir = env.get("CHANAMQ_SHARD_DIR") \
            or str(config.get("chana.mq.shard.dir") or "")
        return cls(
            count=count,
            host=config.str("chana.mq.cluster.host"),
            base_port=config.int("chana.mq.cluster.port") - index,
            dir=sdir,
        )

    # -- layout ------------------------------------------------------------

    def name(self, index: int) -> str:
        return f"{self.host}:{self.base_port + index}"

    def names(self) -> list[str]:
        return [self.name(i) for i in range(self.count)]

    def uds_path(self, index: int) -> str:
        return os.path.join(self.dir, f"shard-{index}.sock")

    def handoff_path(self, index: int) -> str:
        return os.path.join(self.dir, f"handoff-{index}.sock")

    def uds_map_for(self, index: int) -> dict[str, str]:
        """Sibling member name -> Unix-socket path (self excluded)."""
        return {
            self.name(i): self.uds_path(i)
            for i in range(self.count) if i != index
        }

    def seeds_for(self, index: int, external: Iterable[str] = ()) -> list[str]:
        """Every sibling plus any cross-machine seeds from the config."""
        seeds = [self.name(i) for i in range(self.count) if i != index]
        for seed in external:
            if seed and seed not in seeds:
                seeds.append(seed)
        return seeds
