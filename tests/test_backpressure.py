"""Inbound publisher backpressure + bounded memory under hostile load.

VERDICT r3 #2: a fast publisher of transient messages into a consumerless
queue must not grow RAM without bound. Two mechanisms compose:

- per-queue depth passivation pages transient bodies to the store
  (tests in test_passivation.py);
- the broker-wide memory gate stops READING publishing connections above
  chana.mq.memory.high-watermark and resumes below the low watermark,
  sending Connection.Blocked/Unblocked to capable clients (exceeds the
  reference, which never implemented them — README.md:10-22; its
  backpressure was akka-streams demand + TCP, SURVEY.md §7.3).
"""

import asyncio

import pytest

from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.connection import AMQPConnection
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

BODY = b"z" * 1024


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError("condition not reached in time")
        await asyncio.sleep(interval)


async def test_transient_flood_bounded_resident_no_disconnect(tmp_path):
    """The VERDICT acceptance test: flood transient messages into a
    consumerless queue; resident_bytes stays bounded, the connection stays
    up, and the gauge is visible via /admin/metrics."""
    broker = Broker(store=SqliteStore(str(tmp_path / "bp.db")),
                    queue_max_resident=8)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    admin = AdminServer(broker, host="127.0.0.1", port=0)
    await admin.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("flood_q", durable=True)

    n = 300
    for _ in range(n):
        ch.basic_publish(BODY, routing_key="flood_q")  # transient

    queue = broker.vhosts["/"].queues["flood_q"]
    await wait_for(lambda: len(queue.messages) == n)
    # bounded: at most watermark+1 resident bodies (plus slack for the
    # in-flight page-out pass), not n
    assert broker.resident_bytes <= 16 * len(BODY), broker.resident_bytes
    assert not c.closed  # no disconnect

    # the gauge is exported on /admin/metrics
    reader, writer = await asyncio.open_connection("127.0.0.1", admin.bound_port)
    writer.write(b"GET /admin/metrics HTTP/1.1\r\n\r\n")
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    import json

    payload = json.loads(raw.split(b"\r\n\r\n", 1)[1])
    assert payload["resident_bytes"] == broker.resident_bytes
    assert payload["memory_blocked"] is False

    # everything is still consumable, in order, bodies intact
    got = 0
    while True:
        m = await ch.basic_get("flood_q", no_ack=True)
        if m is None:
            break
        assert m.body == BODY
        got += 1
    assert got == n
    await c.close()
    await admin.stop()
    await srv.stop()


async def test_memory_gate_blocks_and_unblocks_publisher(tmp_path):
    """Above the high watermark the broker stops reading the publisher and
    sends Connection.Blocked; after a consumer drains below the low
    watermark it resumes and sends Unblocked; nothing is lost."""
    broker = Broker(store=SqliteStore(str(tmp_path / "gate.db")),
                    queue_max_resident=0,          # passivation off: force
                    memory_high_watermark=20 * 1024,  # the gate to do the work
                    memory_low_watermark=4 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()

    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    pch = await pub.channel()
    await pch.queue_declare("gate_q")

    n = 120  # 120 KiB >> 20 KiB high watermark
    for _ in range(n):
        pch.basic_publish(BODY, routing_key="gate_q")

    await wait_for(lambda: broker.blocked)
    # capable client got Connection.Blocked
    await wait_for(lambda: pub.server_blocked)
    assert not pub.closed
    blocked_at = broker.resident_bytes
    assert blocked_at > broker.memory_high_watermark

    # a consumer-only connection is NOT gated: it can drain
    con = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cch = await con.channel()
    received = []

    def cb(msg):
        received.append(msg)

    await cch.basic_consume("gate_q", cb, no_ack=True)
    # draining lowers resident bytes below low watermark -> gate reopens,
    # the parked publisher connection resumes reading, the rest flows
    await wait_for(lambda: len(received) == n, timeout=30)
    await wait_for(lambda: not broker.blocked)
    await wait_for(lambda: not pub.server_blocked)

    # the unblocked publisher works again end-to-end
    pch.basic_publish(b"after", routing_key="gate_q")
    await wait_for(lambda: len(received) == n + 1)
    assert received[-1].body == b"after"
    assert all(m.body == BODY for m in received[:n])

    await pub.close()
    await con.close()
    await srv.stop()


async def test_server_stop_while_publisher_gated(tmp_path):
    """Review regression: BrokerServer.stop() must not deadlock on a
    publisher parked at the memory gate (the bounded gate wait re-checks
    closing)."""
    broker = Broker(store=SqliteStore(str(tmp_path / "stop.db")),
                    queue_max_resident=0,
                    memory_high_watermark=8 * 1024,
                    memory_low_watermark=2 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    pch = await pub.channel()
    await pch.queue_declare("stop_q")
    for _ in range(32):
        pch.basic_publish(BODY, routing_key="stop_q")
    await wait_for(lambda: broker.blocked)
    await asyncio.wait_for(srv.stop(), 10)  # used to hang forever
    await pub.close()


async def test_frozen_consumer_bounds_write_buffer():
    """Outbound backpressure (SURVEY §7.3): a consumer that stops reading
    must cap its connection's write buffer near WRITE_HIGH_WATERMARK —
    queue dispatch skips saturated connections and parks the backlog in
    the queue — and drain completely once the consumer resumes."""
    broker = Broker()
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    c_cons = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    chc = await c_cons.channel()
    await chc.queue_declare("stall_q")
    await chc.basic_consume("stall_q", lambda m: None, no_ack=True)
    await asyncio.sleep(0.1)
    c_cons.reader._transport.pause_reading()  # freeze the consumer socket

    c_prod = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    chp = await c_prod.channel()
    await chp.confirm_select()
    body = b"z" * 10_000
    for i in range(1500):  # ~15 MB into a frozen consumer
        chp.basic_publish(body, routing_key="stall_q")
        if i % 500 == 499:
            await chp.wait_unconfirmed_below(1)
    await chp.wait_unconfirmed_below(1)
    bufs = [cn._out_bytes + cn._egress_bytes for cn in srv._connections]
    queue = broker.vhosts["/"].queues["stall_q"]
    assert max(bufs) < 6 * 1024 * 1024, f"write buffer unbounded: {bufs}"
    assert len(queue.messages) > 0

    c_cons.reader._transport.resume_reading()
    await wait_for(
        lambda: not queue.messages
        and all(cn._out_bytes + cn._egress_bytes == 0
                for cn in srv._connections), timeout=30)
    await c_prod.close()
    await c_cons.close()
    await srv.stop()


async def test_token_consumer_does_not_bypass_gate(tmp_path):
    """VERDICT r4 weak #2: a flooder holding one consumer on a dummy queue
    must still be stopped by the gate — publish commands are HELD at the
    connection (bounded), not executed, regardless of consumers. The flood
    stops being absorbed (published_msgs plateaus) while an independent
    consumer still drains; after the drain the gate reopens, the held
    publishes release, and everything lands."""
    broker = Broker(store=SqliteStore(str(tmp_path / "tok.db")),
                    queue_max_resident=0,          # passivation off: force
                    memory_high_watermark=20 * 1024,  # the gate to do the work
                    memory_low_watermark=4 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()

    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    pch = await pub.channel()
    await pch.queue_declare("flood_q")
    await pch.queue_declare("dummy_q")
    # the token consumer (the bypass vector): dummy queue, never a message
    await pch.basic_consume("dummy_q", lambda m: None, no_ack=True)

    n = 600  # 600 KiB >> 20 KiB high watermark, > PARK_BUF_MAX past it
    for _ in range(n):
        pch.basic_publish(BODY, routing_key="flood_q")

    await wait_for(lambda: broker.blocked)
    # the flooder kept publishing past the gate: its publishes are held
    await wait_for(lambda: any(c._held for c in srv._connections))
    await asyncio.sleep(0.5)
    absorbed = broker.metrics.published_msgs
    # held: nothing further executes despite the client still pushing
    await asyncio.sleep(0.5)
    assert broker.metrics.published_msgs == absorbed
    assert absorbed < n  # the flood did NOT fully land
    # resident stays near the watermark; held bodies are bounded and on
    # their own gauge
    assert broker.resident_bytes < 2 * broker.memory_high_watermark \
        + 2 * AMQPConnection.PARK_BUF_MAX
    # design bound: the cap is checked between read chunks, so worst case
    # is cap + one full chunk of holds (bodies + per-command overhead)
    assert 0 < broker.held_bytes <= 3 * AMQPConnection.PARK_BUF_MAX

    # an independent consumer drains below the low watermark -> unblock ->
    # the parked flood resumes and lands completely, nothing lost
    con = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cch = await con.channel()
    received = []
    await cch.basic_consume("flood_q", received.append, no_ack=True)
    await wait_for(lambda: len(received) == n, timeout=30)
    assert all(m.body == BODY for m in received)
    await wait_for(lambda: broker.held_bytes == 0)

    await pub.close()
    await con.close()
    await srv.stop()


async def test_store_growth_gate(tmp_path):
    """VERDICT r4 weak #2 (second half): when page-out absorbs a transient
    flood, RAM stays flat but the store grows — chana.mq.store.max-bytes
    must close the gate, bound the store, and reopen after a drain."""
    broker = Broker(store=SqliteStore(str(tmp_path / "growth.db")),
                    queue_max_resident=4,          # page transient bodies out
                    memory_high_watermark=64 * 1024 * 1024,  # RAM gate idle
                    message_sweep_interval_s=0.05,
                    store_max_bytes=192 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()

    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    pch = await pub.channel()
    await pch.queue_declare("pg_q")

    n = 1500  # 1.5 MiB of transient bodies >> 192 KiB store cap

    async def flood() -> None:
        # paced: the store gate SAMPLES (one check per sweep tick), so a
        # single-burst flood can fully land between two samples — the gate
        # bounds sustained floods, not one unsampled burst
        for i in range(n):
            pch.basic_publish(BODY, routing_key="pg_q")
            if i % 50 == 49:
                await asyncio.sleep(0.02)

    flood_task = asyncio.create_task(flood())
    await wait_for(lambda: broker.blocked, timeout=15)
    assert broker._store_over and not broker._mem_over
    await asyncio.sleep(0.3)  # a few sweep samples while parked
    # bounded: cap + one sweep tick of unsampled flood + the in-flight read
    # chunk that was mid-processing at gate close + sqlite page overhead
    bound = (broker.store_max_bytes + AMQPConnection.PARK_BUF_MAX
             + 512 * 1024)
    assert broker.store_bytes < bound, broker.store_bytes
    assert broker.resident_bytes < 1024 * 1024  # page-out kept RAM flat

    # drain from another connection: deletes shrink live data (freelist),
    # the sweep sees it, the gate reopens, the rest of the flood lands
    con = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cch = await con.channel()
    got = 0
    deadline = asyncio.get_event_loop().time() + 60
    while got < n:
        assert asyncio.get_event_loop().time() < deadline, got
        m = await cch.basic_get("pg_q", no_ack=True)
        if m is None:
            await asyncio.sleep(0.05)
            continue
        assert m.body == BODY
        got += 1
    await wait_for(lambda: not broker.blocked, timeout=15)
    assert got == n
    await flood_task

    await pub.close()
    await con.close()
    await srv.stop()


async def test_parked_dead_peer_reaped_healthy_survives(tmp_path):
    """VERDICT r4 weak #3: heartbeat reaping must keep working while the
    broker is blocked. A gated publisher whose peer goes silent is reaped
    within the normal 2x-interval deadline (non-publish frames keep being
    processed while publishes are held, so silence IS observable); a gated
    publisher that keeps heartbeating survives the whole block."""
    broker = Broker(store=SqliteStore(str(tmp_path / "reap.db")),
                    queue_max_resident=0,
                    memory_high_watermark=8 * 1024,
                    memory_low_watermark=2 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=1)
    await srv.start()

    dead = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    dch = await dead.channel()
    live = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    lch = await live.channel()
    await dch.queue_declare("reap_q")
    for _ in range(16):  # 16 KiB > 8 KiB: closes the gate, parks both
        dch.basic_publish(BODY, routing_key="reap_q")
        lch.basic_publish(BODY, routing_key="reap_q")
    await wait_for(lambda: broker.blocked)

    # silent death: stop the dead client's heartbeats (socket stays open)
    dead._heartbeat_task.cancel()
    n_conns = len(srv._connections)
    # reaped within the 2x-interval deadline (+ scheduling slack)
    await wait_for(lambda: len(srv._connections) == n_conns - 1, timeout=8)
    # the healthy gated publisher survived the same window
    assert not live.closed
    assert any(c._has_published for c in srv._connections)

    await live.close()
    await srv.stop()


async def test_same_channel_worker_acks_drain_gate(tmp_path):
    """Review regression: a single-channel publish+consume (manual ack)
    client whose acks are the only drain must not deadlock the gate — acks
    pipelined behind held publishes are exempt from the per-channel hold
    (they settle prior deliveries, which commute with publishes)."""
    broker = Broker(store=SqliteStore(str(tmp_path / "worker.db")),
                    queue_max_resident=0,
                    memory_high_watermark=20 * 1024,
                    memory_low_watermark=4 * 1024)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()

    # the blocked episode can be short (acks drain fast locally): latch it
    # via the listener instead of polling the transient flag
    saw_blocked = []
    broker.blocked_listeners.add(saw_blocked.append)

    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("w_q")
    await ch.basic_qos(prefetch_count=50)
    received = []

    def on_msg(msg):
        received.append(msg)
        ch.basic_ack(msg.delivery_tag)  # ack on the SAME channel

    await ch.basic_consume("w_q", on_msg, no_ack=False)

    n = 400  # 400 KiB >> 20 KiB high watermark
    for _ in range(n):
        ch.basic_publish(BODY, routing_key="w_q")

    await wait_for(lambda: True in saw_blocked, timeout=10)
    # the acks keep flowing despite held publishes on the channel: the
    # gate reopens and every message lands and settles
    await wait_for(lambda: len(received) == n, timeout=30)
    await wait_for(lambda: not broker.blocked, timeout=10)
    await wait_for(lambda: broker.held_bytes == 0, timeout=10)
    queue = broker.vhosts["/"].queues["w_q"]
    await wait_for(lambda: not queue.outstanding and not queue.messages)

    await c.close()
    await srv.stop()
