"""ctypes bindings for the native hot paths (native/chanamq_native.cpp).

Load order: (1) the library pip built at install time
(chanamq_tpu/_chanamq_native*.so, see setup.py), (2) a repo checkout's
native/libchanamq_native.so, compiled on first use when a C++ toolchain is
present. Falls back silently (callers keep the pure-Python implementations)
when no library can be found or built, or CHANAMQ_NATIVE=0.

Exposes:
  NativeFrameParser  — drop-in for amqp.frame.FrameParser
  NativeTopicMatcher — drop-in for broker.matchers.TopicMatcher
"""

from __future__ import annotations

import ctypes
import glob
import logging
import os
import subprocess
import time
from typing import Iterator, Optional

from . import profile
from .amqp.constants import ErrorCode
from .amqp.frame import Frame, FrameError
from .broker.matchers import Matcher

log = logging.getLogger("chanamq.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libchanamq_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "chanamq_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception as exc:
        log.info("native build unavailable: %r", exc)
        return False


def _find_lib() -> Optional[str]:
    src = os.path.join(_NATIVE_DIR, "chanamq_native.cpp")
    # (1) library built by pip at install time, sitting inside the package —
    # unless a repo checkout's source is newer (editable-install dev loop:
    # a stale pip build must not shadow edited native code)
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    installed = sorted(glob.glob(os.path.join(pkg_dir, "_chanamq_native*.so")))
    if installed and not (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(installed[0])):
        return installed[0]
    # (2) repo checkout: make-on-demand in native/
    needs_build = not os.path.exists(_LIB_PATH) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH))
    if needs_build and not _build():
        return None
    return _LIB_PATH


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on demand. None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    _load_attempted = True
    if os.environ.get("CHANAMQ_NATIVE", "1") in ("0", "false", "no"):
        return None
    lib_path = _find_lib()
    if lib_path is None:
        return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as exc:
        log.info("native lib load failed: %r", exc)
        return None
    lib.chana_scan_frames.restype = ctypes.c_int
    lib.chana_scan_frames.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.chana_trie_new.restype = ctypes.c_void_p
    lib.chana_trie_free.argtypes = [ctypes.c_void_p]
    lib.chana_trie_bind.restype = ctypes.c_int
    lib.chana_trie_bind.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.chana_trie_unbind.restype = ctypes.c_int
    lib.chana_trie_unbind.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.chana_trie_route.restype = ctypes.c_int
    lib.chana_trie_route.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.chana_trie_size.restype = ctypes.c_int
    lib.chana_trie_size.argtypes = [ctypes.c_void_p]
    _lib = lib
    log.info("native hot paths loaded from %s", lib_path)
    return _lib


def available() -> bool:
    return load() is not None


_MAX_FRAMES_PER_SCAN = 4096


class NativeFrameParser:
    """Drop-in FrameParser backed by the C scanner: one native call per read
    chunk instead of a Python loop per frame."""

    __slots__ = ("frame_max", "_buf", "_dead", "_lib",
                 "_types", "_channels", "_offsets", "_lengths",
                 "_consumed", "_error")

    def __init__(self, frame_max: int = 0) -> None:
        self.frame_max = frame_max
        self._buf = bytearray()
        self._dead = False
        self._lib = load()
        assert self._lib is not None, "native library unavailable"
        self._types = (ctypes.c_int32 * _MAX_FRAMES_PER_SCAN)()
        self._channels = (ctypes.c_int32 * _MAX_FRAMES_PER_SCAN)()
        self._offsets = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        self._lengths = (ctypes.c_int64 * _MAX_FRAMES_PER_SCAN)()
        self._consumed = ctypes.c_int64()
        self._error = ctypes.c_int32()

    def scan_batches(self, data: bytes) -> Iterator[tuple | FrameError]:
        """Scan a read chunk into frame-index batches WITHOUT creating Frame
        objects: yields ``(raw, n, types, channels, offsets, lengths)``
        tuples (the arrays are reused between yields — consume a batch fully
        before advancing), then a FrameError if the stream is corrupt. The
        connection hot loop walks the arrays directly; feed() adapts them to
        Frame objects for everything else."""
        if self._dead:
            return
        # One buffer->bytes conversion per call (NOT per scan pass — a
        # per-pass copy would be O(n^2) when a backlog accumulates); the
        # rare >_MAX_FRAMES_PER_SCAN continuation slices off the consumed
        # prefix, amortized O(1) per byte.
        if self._buf:
            self._buf += data
            raw = bytes(self._buf)
            self._buf = bytearray()
        else:
            raw = bytes(data)
        while True:
            # batch-granular cost ledger: one stamp pair per scan pass (up
            # to _MAX_FRAMES_PER_SCAN frames), accumulated inside the lazy
            # generator so the native call itself is what gets timed
            prof = profile.ACTIVE
            t_prof = time.perf_counter_ns() if prof is not None else 0
            n = self._lib.chana_scan_frames(
                raw, len(raw), self.frame_max,
                self._types, self._channels, self._offsets, self._lengths,
                _MAX_FRAMES_PER_SCAN, ctypes.byref(self._consumed),
                ctypes.byref(self._error))
            if prof is not None and n:
                prof.stage_ns[profile.INGRESS_PARSE] += (
                    time.perf_counter_ns() - t_prof)
                prof.stage_calls[profile.INGRESS_PARSE] += n
            if n:
                yield (raw, n, self._types, self._channels,
                       self._offsets, self._lengths)
            consumed = self._consumed.value
            error = self._error.value
            if error:
                self._dead = True
                if error == 1:
                    yield FrameError(ErrorCode.FRAME_ERROR,
                                     "unknown frame type")
                elif error == 2:
                    yield FrameError(
                        ErrorCode.FRAME_ERROR,
                        f"frame exceeds negotiated frame-max {self.frame_max}")
                else:
                    yield FrameError(ErrorCode.FRAME_ERROR,
                                     "missing frame-end octet")
                return
            if n < _MAX_FRAMES_PER_SCAN:
                if consumed < len(raw):
                    self._buf = bytearray(raw[consumed:])
                return
            raw = raw[consumed:]

    def feed(self, data: bytes) -> Iterator[Frame | FrameError]:
        for batch in self.scan_batches(data):
            if isinstance(batch, FrameError):
                yield batch
                return
            raw, n, types, channels, offsets, lengths = batch
            for i in range(n):
                off = offsets[i]
                yield Frame(types[i], channels[i], raw[off:off + lengths[i]])


class NativeTopicMatcher(Matcher):
    """Drop-in TopicMatcher routing through the C++ trie. The (pattern,
    queue) registry stays Python-side for bindings()/recovery; the trie is
    the routing fast path."""

    def __init__(self) -> None:
        lib = load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.chana_trie_new())
        self._queue_ids: dict[str, int] = {}
        self._queue_names: dict[int, str] = {}
        self._next_id = 1
        self._patterns: dict[tuple[str, str], int] = {}
        self.binding_table = self._patterns
        self._out = (ctypes.c_int32 * 4096)()

    def __del__(self) -> None:  # pragma: no cover
        try:
            if self._handle:
                self._lib.chana_trie_free(self._handle)
        except Exception:
            pass

    def _queue_id(self, queue: str) -> int:
        qid = self._queue_ids.get(queue)
        if qid is None:
            qid = self._next_id
            self._next_id += 1
            self._queue_ids[queue] = qid
            self._queue_names[qid] = queue
        return qid

    def bind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if (key, queue) in self._patterns:
            return False
        self._patterns[(key, queue)] = 1
        self._lib.chana_trie_bind(
            self._handle, key.encode(), self._queue_id(queue))
        return True

    def unbind(self, key: str, queue: str, arguments: Optional[dict] = None) -> bool:
        if self._patterns.pop((key, queue), None) is None:
            return False
        self._lib.chana_trie_unbind(
            self._handle, key.encode(), self._queue_id(queue))
        return True

    def unbind_queue(self, queue: str) -> int:
        keys = [k for (k, q) in self._patterns if q == queue]
        for key in keys:
            self.unbind(key, queue)
        return len(keys)

    def route(self, key: str, headers: Optional[dict] = None) -> set[str]:
        kb = key.encode()
        n = self._lib.chana_trie_route(self._handle, kb, self._out, len(self._out))
        while n > len(self._out):
            # returned count is the TOTAL match count: grow and re-route
            # instead of silently truncating at the buffer size
            self._out = (ctypes.c_int32 * max(n, len(self._out) * 2))()
            n = self._lib.chana_trie_route(
                self._handle, kb, self._out, len(self._out))
        return {self._queue_names[self._out[i]] for i in range(n)}

    def bindings(self) -> list[tuple[str, str, Optional[dict]]]:
        return [(k, q, None) for (k, q) in sorted(self._patterns)]

    def is_empty(self) -> bool:
        return not self._patterns
