"""Seeded deterministic fault plans.

A ``FaultPlan`` is a list of named ``FaultRule``s plus one RNG seed. Every
injection site the broker threads (store read/write/delete/flush, rpc
call/connect/read-loop, data-plane send/read, replication shipping) calls
``decide(site, ...)`` once per operation; the plan answers with a ``Fault``
to inject or ``None``.

Determinism contract: whether a rule fires on its Nth *matching* invocation
is a pure function of ``(seed, rule name, N)`` — each rule draws from its
own ``random.Random`` keyed by the seed and a stable CRC of the rule name,
one draw per eligible invocation. Two runs with the same seed therefore
carry the identical fault schedule: the same invocation indices fire, in
the same order, regardless of wall-clock timing. ``schedule_preview``
materializes that schedule up front so harnesses can fingerprint it.

Triggers compose per rule:

- ``probability`` — chance a matching invocation fires (drawn from the
  rule's seeded RNG; 1.0 = always);
- ``count``      — max total fires (None = unlimited);
- ``after`` / ``until`` — the matching-invocation window [after, until)
  inside which the rule is armed (both in invocation index, not time, so
  the window is deterministic too).

Fault kinds and what the seams do with them:

``latency``     sleep ``delay_ms`` then proceed
``error``       raise at the seam (store: OSError; rpc/data: RpcError)
``drop``        lose the unit silently (a frame, an event, a ship batch)
``disconnect``  close the transport so the reconnect path runs
``corrupt``     desync the byte stream (read loops raise FrameTooLarge)
``crash``       invoke the harness-registered crash handler for ``nodes``
``partition``   like ``error`` but only when the ctx peer is in ``nodes``
                (A<->B partition = traffic toward the named nodes fails)
``pressure``    inflate the flow accountant's ``chaos`` component by
                ``inflate_bytes`` for this sweep tick (site ``flow.tick``)
                so memory-overload behavior is injectable deterministically
"""

from __future__ import annotations

import hashlib
import json
import random
import zlib
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import Any, Optional

FAULT_KINDS = (
    "latency", "error", "drop", "disconnect", "corrupt", "crash", "partition",
    "pressure",
)

# fire-log ring bound: enough to replay a soak, small enough to forget
_FIRE_LOG_MAX = 4096


@dataclass(slots=True)
class Fault:
    """One injected fault, handed to the seam that asked."""

    kind: str
    rule: str
    delay_s: float = 0.0
    code: str = "chaos"
    message: str = ""
    inflate_bytes: int = 0


@dataclass
class FaultRule:
    """One named fault source. See module docstring for field semantics."""

    name: str
    kind: str
    sites: list[str] = field(default_factory=lambda: ["*"])
    probability: float = 1.0
    count: Optional[int] = None
    after: int = 0
    until: Optional[int] = None
    peer: Optional[str] = None          # glob on the ctx peer ("host:port")
    delay_ms: float = 0.0
    code: str = "chaos"
    message: str = ""
    nodes: list[str] = field(default_factory=list)  # crash / partition targets
    inflate_bytes: int = 0              # pressure: accounted-cost inflation

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if not self.name:
            raise ValueError("fault rule needs a name")
        self.probability = min(1.0, max(0.0, float(self.probability)))

    def matches_site(self, site: str) -> bool:
        return any(fnmatchcase(site, pattern) for pattern in self.sites)

    def matches_ctx(self, peer: str) -> bool:
        if self.kind == "partition":
            # partition semantics: only traffic TOWARD the named nodes fails
            return peer in self.nodes
        if self.peer is not None:
            return fnmatchcase(peer, self.peer)
        return True

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "sites": list(self.sites),
            "probability": self.probability, "count": self.count,
            "after": self.after, "until": self.until, "peer": self.peer,
            "delay_ms": self.delay_ms, "code": self.code,
            "message": self.message, "nodes": list(self.nodes),
            "inflate_bytes": self.inflate_bytes,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "FaultRule":
        known = {
            "name", "kind", "sites", "probability", "count", "after",
            "until", "peer", "delay_ms", "code", "message", "nodes",
            "inflate_bytes",
        }
        return cls(**{k: v for k, v in data.items() if k in known})


class _RuleState:
    """Mutable per-rule run state: the seeded RNG plus the counters the
    admin endpoint dumps."""

    __slots__ = ("rule", "rng", "invocations", "fires")

    def __init__(self, rule: FaultRule, seed: int) -> None:
        self.rule = rule
        self.rng = random.Random(_rule_seed(seed, rule.name))
        self.invocations = 0
        self.fires = 0


def _rule_seed(seed: int, name: str) -> int:
    # zlib.crc32, not hash(): str hashing is salted per process and would
    # break the cross-run determinism contract
    return (int(seed) * 1_000_003) ^ zlib.crc32(name.encode("utf-8"))


class FaultPlan:
    """A seeded set of fault rules with per-rule fire accounting."""

    def __init__(self, seed: int, rules: list[FaultRule]) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in plan: {names}")
        self.seed = int(seed)
        self.rules = list(rules)
        self._states = [_RuleState(r, self.seed) for r in self.rules]
        # realized fire sequence: (global fire index, rule, site), bounded
        self.fire_log: list[tuple[int, str, str]] = []
        self.total_fires = 0

    # -- the decision ------------------------------------------------------

    def decide(self, site: str, peer: str = "") -> Optional[Fault]:
        """One injection-point consultation. First armed rule that matches
        and draws a fire wins (rules are ordered; put rare ones first)."""
        for state in self._states:
            rule = state.rule
            if not rule.matches_site(site) or not rule.matches_ctx(peer):
                continue
            state.invocations += 1
            if not self._eligible(state):
                continue
            if rule.probability < 1.0 and state.rng.random() >= rule.probability:
                continue
            state.fires += 1
            self.total_fires += 1
            if len(self.fire_log) < _FIRE_LOG_MAX:
                self.fire_log.append((self.total_fires, rule.name, site))
            return Fault(
                kind=rule.kind, rule=rule.name,
                delay_s=rule.delay_ms / 1000.0, code=rule.code,
                message=rule.message or f"injected by rule {rule.name!r}",
                inflate_bytes=rule.inflate_bytes)
        return None

    @staticmethod
    def _eligible(state: _RuleState) -> bool:
        rule = state.rule
        n = state.invocations  # 1-based index of THIS invocation
        if n <= rule.after:
            return False
        if rule.until is not None and n > rule.until:
            return False
        if rule.count is not None and state.fires >= rule.count:
            return False
        return True

    # -- introspection -----------------------------------------------------

    def counters(self) -> dict[str, dict]:
        return {
            s.rule.name: {
                "kind": s.rule.kind, "invocations": s.invocations,
                "fires": s.fires,
            }
            for s in self._states
        }

    def schedule_preview(self, horizon: int = 1000) -> dict[str, list[int]]:
        """The deterministic fire schedule: for each rule, the matching-
        invocation indices (1-based) that would fire within ``horizon``
        invocations. Computed from fresh RNGs — never consumes plan state —
        so it is a pure function of (seed, rules) and safe to fingerprint."""
        out: dict[str, list[int]] = {}
        for rule in self.rules:
            rng = random.Random(_rule_seed(self.seed, rule.name))
            fires: list[int] = []
            for n in range(1, horizon + 1):
                if n <= rule.after:
                    continue
                if rule.until is not None and n > rule.until:
                    break
                if rule.count is not None and len(fires) >= rule.count:
                    break
                if rule.probability >= 1.0 or rng.random() < rule.probability:
                    fires.append(n)
            out[rule.name] = fires
        return out

    def fingerprint(self, horizon: int = 1000) -> str:
        """SHA-256 over (seed, rule specs, fire schedule): two plans with
        the same seed and rules — across processes and runs — fingerprint
        identically; any drift in the schedule changes it. Endpoint
        bindings (``nodes``) are excluded: they name this deployment's
        ephemeral host:port strings, not anything that alters the
        per-invocation decision schedule."""
        specs = []
        for rule in self.rules:
            spec = rule.to_dict()
            spec.pop("nodes", None)
            specs.append(spec)
        blob = json.dumps({
            "seed": self.seed,
            "rules": specs,
            "schedule": self.schedule_preview(horizon),
        }, sort_keys=True).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()

    # -- (de)serialization (the /admin/chaos install body) -----------------

    def to_dict(self) -> dict:
        return {"seed": self.seed, "rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, data: Any) -> "FaultPlan":
        if not isinstance(data, dict):
            raise ValueError("fault plan must be a JSON object")
        rules = data.get("rules")
        if not isinstance(rules, list) or not rules:
            raise ValueError("fault plan needs a non-empty 'rules' list")
        return cls(
            seed=int(data.get("seed", 0)),
            rules=[FaultRule.from_dict(r) for r in rules])
