"""Ops surface tests: config tree, admin REST, TLS listener."""

import asyncio
import json
import ssl
import subprocess

import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.config import Config, ConfigError, parse_duration_s, parse_size_bytes
from chanamq_tpu.rest.admin import AdminServer

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_config_defaults():
    cfg = Config(env={})
    assert cfg.int("chana.mq.amqp.port") == 5672
    assert cfg.size_bytes("chana.mq.amqp.connection.frame-max") == 128 * 1024
    assert cfg.duration_s("chana.mq.amqp.connection.heartbeat") == 30.0
    assert cfg.str("chana.mq.vhost.default") == "/"


def test_config_env_override():
    cfg = Config(env={"CHANAMQ_AMQP_PORT": "5673",
                      "CHANAMQ_AMQP_CONNECTION_HEARTBEAT": "10s",
                      "CHANAMQ_ADMIN_ENABLED": "false"})
    assert cfg.int("chana.mq.amqp.port") == 5673
    assert cfg.duration_s("chana.mq.amqp.connection.heartbeat") == 10.0
    assert cfg.bool("chana.mq.admin.enabled") is False


def test_config_file_layer(tmp_path):
    f = tmp_path / "broker.json"
    f.write_text(json.dumps({
        "amqp": {"port": 6000, "connection": {"frame-max": "64KiB"}},
        "chana.mq.admin.port": 16000,
    }))
    cfg = Config(file=str(f), env={})
    assert cfg.int("chana.mq.amqp.port") == 6000
    assert cfg.size_bytes("chana.mq.amqp.connection.frame-max") == 64 * 1024
    assert cfg.int("chana.mq.admin.port") == 16000


def test_config_overrides_win(tmp_path):
    f = tmp_path / "c.json"
    f.write_text(json.dumps({"amqp": {"port": 6000}}))
    cfg = Config({"chana.mq.amqp.port": 7000}, file=str(f), env={})
    assert cfg.int("chana.mq.amqp.port") == 7000


def test_duration_and_size_parsing():
    assert parse_duration_s("500ms") == 0.5
    assert parse_duration_s("2m") == 120.0
    assert parse_duration_s("1h") == 3600.0
    assert parse_duration_s("infinite") is None
    assert parse_duration_s(15) == 15.0
    assert parse_size_bytes("4MiB") == 4 * 1024 * 1024
    assert parse_size_bytes("1KB") == 1000
    assert parse_size_bytes(4096) == 4096
    with pytest.raises(ConfigError):
        parse_duration_s("eleventy")


# ---------------------------------------------------------------------------
# admin REST
# ---------------------------------------------------------------------------


async def http_req(port: int, path: str, method: str = "GET") -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(65536), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(body) if body else {}


@pytest.fixture
async def stack():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    yield server, admin
    await admin.stop()
    await server.stop()


async def test_admin_vhost_put_delete(stack):
    server, admin = stack
    status, body = await http_req(admin.bound_port, "/admin/vhost/put/tenant1", "POST")
    assert status == 200 and body["ok"]
    assert "tenant1" in server.broker.vhosts
    # AMQP clients can use it immediately
    c = await AMQPClient.connect("127.0.0.1", server.bound_port, vhost="tenant1")
    await c.close()
    status, body = await http_req(admin.bound_port, "/admin/vhost/delete/tenant1", "POST")
    assert status == 200 and body["ok"]
    assert "tenant1" not in server.broker.vhosts


async def test_admin_overview_and_queues(stack):
    server, admin = stack
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await c.channel()
    await ch.queue_declare("adm_q", durable=True)
    ch.basic_publish(b"x", routing_key="adm_q")
    await asyncio.sleep(0.05)

    status, overview = await http_req(admin.bound_port, "/admin/overview")
    assert status == 200
    assert overview["vhosts"]["/"]["queues"] == 1
    assert overview["vhosts"]["/"]["messages"] == 1

    status, queues = await http_req(admin.bound_port, "/admin/queues/%2F")
    assert status == 200
    assert queues[0]["name"] == "adm_q"
    assert queues[0]["messages"] == 1
    assert queues[0]["durable"] is True

    status, metrics = await http_req(admin.bound_port, "/admin/metrics")
    assert status == 200
    assert metrics["published_msgs"] == 1

    status, exchanges = await http_req(admin.bound_port, "/admin/exchanges/%2F")
    assert status == 200
    assert any(e["name"] == "(default)" for e in exchanges)
    await c.close()


async def test_admin_unknown_path_404(stack):
    _, admin = stack
    status, _ = await http_req(admin.bound_port, "/admin/nope")
    assert status == 404
    status, _ = await http_req(admin.bound_port, "/favicon.ico")
    assert status == 404


async def test_admin_known_path_wrong_method_405(stack):
    _, admin = stack
    # known GET paths refuse POST with 405 (not a blanket 404) and name
    # the allowed method in the body
    status, body = await http_req(admin.bound_port, "/metrics", "POST")
    assert status == 405 and body["error"] == "use GET"
    status, body = await http_req(admin.bound_port, "/admin/overview", "POST")
    assert status == 405 and body["error"] == "use GET"
    status, body = await http_req(admin.bound_port, "/admin/streams", "POST")
    assert status == 405
    # mutating vhost paths refuse GET the same way
    status, body = await http_req(admin.bound_port, "/admin/vhost/put/x")
    assert status == 405 and body["error"] == "use POST"
    # unknown paths keep 404 regardless of method
    status, _ = await http_req(admin.bound_port, "/admin/nope", "POST")
    assert status == 404


# ---------------------------------------------------------------------------
# TLS (AMQPS)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    path = tmp_path_factory.mktemp("certs")
    cert, key = str(path / "cert.pem"), str(path / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    return cert, key


async def test_amqps_listener(certs):
    certfile, keyfile = certs
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(certfile, keyfile)
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                          tls_port=0, ssl_context=server_ctx)
    await server.start()
    try:
        tls_port = server._servers[1].sockets[0].getsockname()[1]
        client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client_ctx.check_hostname = False
        client_ctx.verify_mode = ssl.CERT_NONE
        c = await AMQPClient.connect("127.0.0.1", tls_port, ssl=client_ctx)
        ch = await c.channel()
        await ch.queue_declare("tls_q")
        ch.basic_publish(b"over-tls", routing_key="tls_q")
        await asyncio.sleep(0.05)
        msg = await ch.basic_get("tls_q", no_ack=True)
        assert msg.body == b"over-tls"
        await c.close()
    finally:
        await server.stop()


async def test_admin_mutations_require_post(stack):
    """GET on a mutating endpoint must be rejected (CSRF hardening; the
    reference used GET here, which is browser-triggerable)."""
    server, admin = stack
    status, _ = await http_req(admin.bound_port, "/admin/vhost/put/evil")
    assert status == 405
    assert "evil" not in server.broker.vhosts


# ---------------------------------------------------------------------------
# listener resource limits (reference: ServerSettings max-connections /
# backlog, Settings.scala:141-219)
# ---------------------------------------------------------------------------


async def test_max_connections_refuses_excess_cleanly():
    """Connections beyond chana.mq.server.max-connections are refused with
    a TCP close before the handshake, while existing connections keep
    working undisturbed."""
    from chanamq_tpu.client import AMQPClient

    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                          max_connections=2)
    await server.start()
    try:
        c1 = await AMQPClient.connect("127.0.0.1", server.bound_port)
        c2 = await AMQPClient.connect("127.0.0.1", server.bound_port)
        # third connection: TCP accepted then closed pre-handshake
        with pytest.raises((ConnectionError, asyncio.IncompleteReadError,
                            EOFError, OSError)):
            await AMQPClient.connect("127.0.0.1", server.bound_port)
        assert server.refused_connections == 1
        # existing connections unaffected: full declare/publish/get cycle
        ch = await c1.channel()
        await ch.queue_declare("lim_q")
        ch.basic_publish(b"still-alive", routing_key="lim_q")
        await c1.drain()
        for _ in range(50):
            msg = await ch.basic_get("lim_q", no_ack=True)
            if msg is not None:
                break
            await asyncio.sleep(0.02)
        assert msg is not None and bytes(msg.body) == b"still-alive"
        await c2.close()
        # a slot freed: new connections are admitted again
        c3 = await AMQPClient.connect("127.0.0.1", server.bound_port)
        await c3.close()
        await c1.close()
    finally:
        await server.stop()


def test_listener_limit_knobs_from_config():
    from chanamq_tpu.config import Config

    cfg = Config(overrides={"chana.mq.admin.enabled": False,
                            "chana.mq.server.max-connections": 7,
                            "chana.mq.server.backlog": 9})
    server = BrokerServer.from_config(cfg)
    assert server.max_connections == 7
    assert server.backlog == 9


async def test_admin_cluster_endpoint(stack):
    server, admin = stack
    # single node, no cluster: endpoint reports disabled
    status, body = await http_req(admin.bound_port, "/admin/cluster")
    assert status == 200 and body == {"enabled": False}

    # with a live 2-node cluster: membership + ownership are visible
    from chanamq_tpu.broker.server import BrokerServer as BS
    from chanamq_tpu.cluster.node import ClusterNode

    cl = ClusterNode(server.broker, "127.0.0.1", 0, [],
                     heartbeat_interval_s=0.2, failure_timeout_s=5)
    peer = peer_srv = None
    try:
        await cl.start()
        peer_srv = BS(host="127.0.0.1", port=0, heartbeat_s=0)
        await peer_srv.start()
        peer = ClusterNode(peer_srv.broker, "127.0.0.1", 0, [cl.name],
                           heartbeat_interval_s=0.2, failure_timeout_s=5)
        await peer.start()
        for _ in range(100):
            if len(cl.membership.alive_members()) == 2:
                break
            await asyncio.sleep(0.05)
        status, body = await http_req(admin.bound_port, "/admin/cluster")
        assert status == 200
        assert body["enabled"] and body["self"] == cl.name
        assert set(body["alive"]) == {cl.name, peer.name}
        assert all("incarnation" in m for m in body["members"].values())
    finally:
        if peer is not None:
            await peer.stop()
        if peer_srv is not None:
            await peer_srv.stop()
        await cl.stop()


async def test_sigterm_graceful_drain(tmp_path):
    """SIGTERM on a live node exits 0 after draining: connections tear
    down (unacked in-flight deliveries requeue durably), store buffers
    flush — nothing confirmed is lost across the restart (the analogue of
    the reference's JVM shutdown hooks)."""
    import json as jsonlib
    import signal
    import subprocess
    import sys

    from chanamq_tpu.amqp.properties import BasicProperties

    db = str(tmp_path / "g.db")
    cfg_path = tmp_path / "n.json"
    cfg_path.write_text(jsonlib.dumps({
        "chana.mq.amqp.interface": "127.0.0.1",
        "chana.mq.amqp.port": 0 or 17421,
        "chana.mq.admin.enabled": False,
        "chana.mq.store.path": db,
    }))

    def start():
        return subprocess.Popen(
            [sys.executable, "-m", "chanamq_tpu.broker.server",
             "--config", str(cfg_path), "--log-level", "WARNING"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    async def wait_up():
        for _ in range(150):
            try:
                _, w = await asyncio.open_connection("127.0.0.1", 17421)
                w.close()
                return
            except OSError:
                await asyncio.sleep(0.1)
        raise RuntimeError("node never came up")

    p = start()
    try:
        await wait_up()
        c = await AMQPClient.connect("127.0.0.1", 17421)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare("gq", durable=True)
        persistent = BasicProperties(delivery_mode=2)
        for i in range(50):
            ch.basic_publish(b"g-%02d" % i, routing_key="gq",
                             properties=persistent)
        await ch.wait_unconfirmed_below(1)
        got = []
        await ch.basic_consume("gq", lambda m: got.append(m))  # never acks
        for _ in range(50):
            if len(got) >= 10:
                break
            await asyncio.sleep(0.05)
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 0
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()

    p = start()
    try:
        await wait_up()
        c2 = await AMQPClient.connect("127.0.0.1", 17421)
        ch2 = await c2.channel()
        ok = await ch2.queue_declare("gq", durable=True, passive=True)
        assert ok.message_count == 50
        await c2.close()
    finally:
        p.terminate()
        p.wait(timeout=10)


async def test_plain_auth_verifies_when_users_configured():
    """chana.mq.auth.users turns SASL PLAIN verification on (the reference
    parses credentials but never verifies; auth listed unimplemented in its
    README). Wrong password or unknown user -> ACCESS_REFUSED close;
    EXTERNAL is refused while a user table is set."""
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.client.client import ConnectionClosedError

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       users={"alice": "s3cret"})
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     username="alice", password="s3cret")
        ch = await c.channel()
        await ch.queue_declare("authed_q")
        await c.close()

        for user, pw in (("alice", "wrong"), ("mallory", "s3cret")):
            with pytest.raises((ConnectionClosedError, OSError,
                                asyncio.IncompleteReadError,
                                asyncio.TimeoutError)):
                await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                         username=user, password=pw)
    finally:
        await srv.stop()


async def test_auth_disabled_accepts_anything():
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     username="anyone", password="anything")
        await c.close()
    finally:
        await srv.stop()


async def test_auth_users_from_config_file_and_env(tmp_path):
    """Dict-valued chana.mq.auth.users survives BOTH config layers: a JSON
    config file (flattening stops at the users mapping) and a JSON-object
    environment variable. Malformed values fail the boot, never fail open."""
    import json as _json

    from chanamq_tpu.config import Config, ConfigError
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.client.client import ConnectionClosedError

    cfg_file = tmp_path / "broker.json"
    cfg_file.write_text(_json.dumps(
        {"auth": {"users": {"bob": "pw1"}},
         "amqp": {"interface": "127.0.0.1", "port": 0,
                  "connection": {"heartbeat": "0s"}}}))
    cfg = Config(file=str(cfg_file), env={})
    assert cfg.get("chana.mq.auth.users") == {"bob": "pw1"}
    srv = BrokerServer.from_config(cfg)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     username="bob", password="pw1")
        await c.close()
        with pytest.raises((ConnectionClosedError, OSError,
                            asyncio.IncompleteReadError,
                            asyncio.TimeoutError)):
            await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     username="bob", password="nope")
    finally:
        await srv.stop()

    # env layer: JSON object required
    cfg2 = Config(env={"CHANAMQ_AUTH_USERS": '{"eve": "pw2"}'})
    assert cfg2.get("chana.mq.auth.users") == {"eve": "pw2"}
    with pytest.raises(ConfigError):
        Config(env={"CHANAMQ_AUTH_USERS": "not-json"})
    with pytest.raises(ConfigError):
        Config(env={"CHANAMQ_AUTH_USERS": '["list"]'})
    # fail-closed on a malformed override too
    with pytest.raises(ConfigError):
        BrokerServer.from_config(
            Config(overrides={"chana.mq.auth.users": "alice:pw"}, env={}))


async def http_text(port: int, path: str) -> tuple[int, str, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    # the server sends Connection: close — read to EOF so a response split
    # across TCP segments can't truncate the body
    raw = await asyncio.wait_for(reader.read(), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    ctype = ""
    for line in head.decode("latin-1").split("\r\n"):
        if line.lower().startswith("content-type:"):
            ctype = line.split(":", 1)[1].strip()
    return status, ctype, body.decode()


async def test_prometheus_metrics_endpoint(stack):
    """GET /metrics serves the Prometheus text exposition format: typed
    broker counters/gauges plus per-queue gauges with vhost/queue labels
    (the reference had no metrics subsystem at all)."""
    server, admin = stack
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    ch = await c.channel()
    await ch.queue_declare("prom_q")
    ch.basic_publish(b"x" * 64, routing_key="prom_q")
    await asyncio.sleep(0.05)

    status, ctype, text = await http_text(admin.bound_port, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    lines = text.splitlines()
    assert "# TYPE chanamq_published_msgs counter" in lines
    assert "# TYPE chanamq_resident_bytes gauge" in lines
    metrics = {}
    for line in lines:
        if line.startswith("#") or not line:
            continue
        name, _, value = line.rpartition(" ")
        metrics[name] = float(value)
    assert metrics["chanamq_published_msgs"] >= 1
    assert metrics['chanamq_queue_messages{vhost="/",queue="prom_q"}'] == 1
    assert metrics['chanamq_queue_ready_bytes{vhost="/",queue="prom_q"}'] == 64
    assert metrics["chanamq_memory_blocked"] == 0
    await c.close()


async def test_vhost_permissions_enforced():
    """chana.mq.auth.permissions: a user with an allowlist may open only
    those vhosts; users absent from the map stay unrestricted."""
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.client.client import ConnectionClosedError

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       users={"tenant": "pw", "admin": "pw"},
                       permissions={"tenant": ["tenant-vh"]})
    await srv.start()
    await srv.broker.create_vhost("tenant-vh")
    try:
        # tenant: allowed vhost works
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     vhost="tenant-vh",
                                     username="tenant", password="pw")
        await c.close()
        # tenant: default vhost refused
        with pytest.raises((ConnectionClosedError, OSError,
                            asyncio.IncompleteReadError,
                            asyncio.TimeoutError)):
            await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                     vhost="/",
                                     username="tenant", password="pw")
        # admin (no allowlist entry): unrestricted
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port, vhost="/",
                                     username="admin", password="pw")
        await c.close()
    finally:
        await srv.stop()


async def test_permissions_config_fails_closed():
    """Allowlists that could silently not be enforced are boot errors:
    permissions without users, or permissions naming unknown users."""
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.config import Config, ConfigError

    with pytest.raises(ConfigError):
        BrokerServer.from_config(Config(
            overrides={"chana.mq.auth.permissions": {"t": ["/"]}}, env={}))
    with pytest.raises(ConfigError):
        BrokerServer.from_config(Config(overrides={
            "chana.mq.auth.users": {"alice": "pw"},
            "chana.mq.auth.permissions": {"bob": ["/"]}}, env={}))
