"""The per-message cost ledger and its aggregate view.

Stage indices are append-only (the Prometheus series and the admin
payload key off the names; reordering would silently re-label recorded
history on a scrape boundary). Two granularities coexist:

- **fine stages** mirror the trace seams (route, enqueue, wal-append,
  deliver, ...) and count *messages* in ``stage_calls``, so
  ``ns / calls`` reads directly as µs per message for that stage; they
  are wall windows (== CPU whenever the loop isn't preempted);
- **top-level stages** (``ingress-cycle``, ``dispatch``,
  ``cluster-push``) wrap whole event-loop work windows measured in
  **loop-thread CPU** (``time.thread_time_ns``), with any top-level
  window that ran inside an awaiting window subtracted back out
  (connection.py's ingress seam), so their sum never double-counts and
  is immune to CPU steal from sibling processes. The attribution claim
  is ``busy_ns / loop_cpu_ns`` — both visible in ``snapshot()``.

Fine stages nest inside top-level ones by design (route happens inside
an ingress cycle); only top-level stages are summed for attribution.
"""

from __future__ import annotations

import asyncio
import gc
import threading
import time
from typing import Optional

import numpy as np

STAGES = (
    "ingress-parse",   # 0  native frame scan, per read-chunk pass
    "route",           # 1  binding resolution (cache, matcher, or kernel)
    "enqueue",         # 2  Message build + store insert + queue.push fanout
    "wal-append",      # 3  WAL frame encode + ingest (pre-commit)
    "wal-commit",      # 4  group-commit write+fsync window (wall, batched)
    "cluster-push",    # 5  origin-side push-batch encode + flush
    "deliver",         # 6  dispatch-pass delivery rendering loop
    "settle",          # 7  ack/reject store cleanup + unrefer
    "flow-throttle",   # 8  publish-gate park window (wall, per episode)
    "dispatch",        # 9  whole coalesced dispatch pass (top-level)
    "ingress-cycle",   # 10 whole read-chunk consume cycle (top-level)
    "gc",              # 11 collector pauses (gc.callbacks)
    "tx-commit",       # 12 Tx.Commit staged replay: scope open -> sealed
)
(INGRESS_PARSE, ROUTE, ENQUEUE, WAL_APPEND, WAL_COMMIT, CLUSTER_PUSH,
 DELIVER, SETTLE, FLOW_THROTTLE, DISPATCH, INGRESS_CYCLE, GC,
 TX_COMMIT) = range(13)

SUBSYSTEMS = (
    "broker", "router", "broker", "wal", "wal", "cluster",
    "broker", "broker", "flow", "broker", "broker", "runtime",
    "broker",
)

# stages whose windows tile the event loop without overlapping: their sum
# is the measured busy time the attribution ratio divides by process CPU
TOP_LEVEL = frozenset({INGRESS_CYCLE, DISPATCH, CLUSTER_PUSH})


class ProfileRuntime:
    """Fixed accumulators + the sampler/watchdog/GC hooks around them.

    ``stage_ns`` / ``stage_calls`` are fixed int64 numpy vectors; seams
    add into them directly (``prof.stage_ns[profile.ROUTE] += dt``) so
    the enabled hot path is two array adds, no method call, no dict, no
    allocation. Everything else (snapshot math, subsystem rollup) runs
    on the admin path only.
    """

    def __init__(
        self,
        node: str = "local",
        metrics=None,
        *,
        sample_hz: int = 0,
        slow_callback_ms: int = 100,
        ring_size: int = 64,
        gc_hook: bool = True,
        broker=None,
    ) -> None:
        self.node = node
        self.metrics = metrics
        self.broker = broker
        self.sample_hz = max(0, int(sample_hz))
        self.slow_callback_ms = max(0, int(slow_callback_ms))
        self.ring_size = max(1, int(ring_size))
        self.gc_hook = gc_hook
        self.stage_ns = np.zeros(len(STAGES), dtype=np.int64)
        self.stage_calls = np.zeros(len(STAGES), dtype=np.int64)
        # attribution denominators since enable: loop-thread CPU (the
        # busy ratio's), process CPU and wall (context). thread_time is
        # per-thread, so _tcpu0_ns is only meaningful against reads from
        # the same thread — start() re-stamps it on the loop thread and
        # snapshot() runs there too (the admin server shares the loop)
        self._tcpu0_ns = time.thread_time_ns()
        self._cpu0_ns = time.process_time_ns()
        self._wall0_ns = time.perf_counter_ns()
        # loop heartbeat for the watchdog (monotonic ns, written by the
        # heartbeat task; read by the sampler thread — GIL-atomic int)
        self.beat_ns = 0
        self.loop_thread_id = threading.get_ident()
        self.sampler = None
        self._hb_task: Optional[asyncio.Task] = None
        self._gc_t0 = 0
        self.gc_pauses = 0
        self.gc_pause_ns = 0
        self.gc_max_pause_ns = 0
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        """Arm the off-ledger parts. Callable without a running loop (unit
        tests drive the ledger alone); the heartbeat task only starts when
        one is available."""
        if self._started:
            return
        self._started = True
        self.loop_thread_id = threading.get_ident()
        self._tcpu0_ns = time.thread_time_ns()
        if self.gc_hook:
            gc.callbacks.append(self._on_gc)
        if loop is None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None
        if loop is not None and self.slow_callback_ms > 0:
            self.beat_ns = time.monotonic_ns()
            self._hb_task = loop.create_task(self._heartbeat())
        if self.sample_hz > 0 or (
                loop is not None and self.slow_callback_ms > 0):
            from .sampler import Sampler

            self.sampler = Sampler(self)
            self.sampler.start()

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        if self.gc_hook:
            try:
                gc.callbacks.remove(self._on_gc)
            except ValueError:
                pass
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        if self.sampler is not None:
            self.sampler.shutdown()
            self.sampler = None

    async def _heartbeat(self) -> None:
        # beats 4x faster than the stall threshold so a missing beat means
        # the loop really is inside one long callback, not between beats
        interval = max(self.slow_callback_ms / 4000.0, 0.005)
        try:
            while True:
                self.beat_ns = time.monotonic_ns()
                await asyncio.sleep(interval)
        except asyncio.CancelledError:
            pass

    # -- GC pauses ----------------------------------------------------------

    def _on_gc(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = time.perf_counter_ns()
        elif phase == "stop" and self._gc_t0:
            dt = time.perf_counter_ns() - self._gc_t0
            self._gc_t0 = 0
            self.stage_ns[GC] += dt
            self.stage_calls[GC] += 1
            self.gc_pauses += 1
            self.gc_pause_ns += dt
            if dt > self.gc_max_pause_ns:
                self.gc_max_pause_ns = dt
            m = self.metrics
            if m is not None:
                m.profile_gc_pauses_total += 1
                m.profile_gc_pause_ns_total += dt

    # -- cold-path helper (tests, non-seam callers) --------------------------

    def note(self, stage: int, dt_ns: int, calls: int = 1) -> None:
        self.stage_ns[stage] += dt_ns
        self.stage_calls[stage] += calls

    # -- aggregate view ------------------------------------------------------

    def snapshot(self) -> dict:
        """The /admin/profile payload: per-stage and per-subsystem µs plus
        the attribution ratio. Pure reads — safe on the admin path."""
        ns = self.stage_ns
        calls = self.stage_calls
        loop_cpu_ns = time.thread_time_ns() - self._tcpu0_ns
        cpu_ns = time.process_time_ns() - self._cpu0_ns
        wall_ns = time.perf_counter_ns() - self._wall0_ns
        stages = {}
        subsystems: dict = {}
        busy_ns = 0
        for i, name in enumerate(STAGES):
            n, c = int(ns[i]), int(calls[i])
            top = i in TOP_LEVEL
            stages[name] = {
                "subsystem": SUBSYSTEMS[i],
                "ns": n,
                "calls": c,
                "us_per_call": round(n / c / 1000.0, 3) if c else None,
                "top_level": top,
            }
            if top:
                busy_ns += n
            if not top and i != GC:
                # subsystem rollup from the fine stages only (the
                # top-level windows contain them; summing both would
                # double-count the same microseconds)
                sub = subsystems.setdefault(
                    SUBSYSTEMS[i], {"ns": 0, "calls": 0})
                sub["ns"] += n
                sub["calls"] += c
        out = {
            # follow the cluster's rename of the node tag (trace does the
            # same): "local" until ClusterNode.start names this node
            "node": (self.broker.trace_node
                     if self.broker is not None else self.node),
            "stages": stages,
            "subsystems": subsystems,
            "busy_ns": busy_ns,
            "loop_cpu_ns": loop_cpu_ns,
            "process_cpu_ns": cpu_ns,
            "wall_ns": wall_ns,
            "attributed_pct": (
                round(busy_ns / loop_cpu_ns * 100.0, 1)
                if loop_cpu_ns > 0 else None),
            "gc": {
                "pauses": self.gc_pauses,
                "pause_ns": self.gc_pause_ns,
                "max_pause_ns": self.gc_max_pause_ns,
            },
        }
        sampler = self.sampler
        if sampler is not None:
            out["sampler"] = {
                "hz": self.sample_hz,
                "samples": sampler.samples,
                "distinct_stacks": len(sampler.stacks),
            }
            out["slow_callbacks"] = {
                "threshold_ms": self.slow_callback_ms,
                "count": sampler.slow_count,
                "recent": list(sampler.ring),
            }
        else:
            out["sampler"] = {"hz": self.sample_hz, "samples": 0,
                              "distinct_stacks": 0}
            out["slow_callbacks"] = {
                "threshold_ms": self.slow_callback_ms,
                "count": 0, "recent": []}
        return out

    def stage_detail(self, name: str) -> Optional[dict]:
        if name not in STAGES:
            return None
        i = STAGES.index(name)
        c = int(self.stage_calls[i])
        n = int(self.stage_ns[i])
        return {
            "stage": name,
            "subsystem": SUBSYSTEMS[i],
            "ns": n,
            "calls": c,
            "us_per_call": round(n / c / 1000.0, 3) if c else None,
            "top_level": i in TOP_LEVEL,
        }

    def collapsed(self) -> str:
        """Folded stacks in flamegraph collapsed format (one ``stack
        count`` line each), hottest first."""
        sampler = self.sampler
        if sampler is None:
            return ""
        return sampler.collapsed()
