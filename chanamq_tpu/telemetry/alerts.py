"""Declarative alert rules, evaluated vectorized over the entity matrix.

Each tick the engine compares every active entity's series against every
rule in two numpy passes (level rules against the latest matrix, growth
rules against the delta matrix) — no per-entity Python loop until an
entity actually breaches. Hysteresis is tick-counted: a rule fires only
after ``for_ticks`` consecutive breaches and resolves only after
``clear_ticks`` consecutive OK ticks, so a gauge grazing its threshold
cannot flap an alert.

Determinism: evaluation is a pure function of the sampled series and the
rule set — no wall clock, no randomness — so under the seeded chaos soak
the same workload produces the same firings and the harness can assert
them exactly (the same bar chaos/plan.py sets for fault schedules).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .store import QUEUE_FIELDS


@dataclass(frozen=True)
class AlertRule:
    """One declarative threshold rule.

    scope "queue": metric is a QUEUE_FIELDS name, evaluated per queue.
    scope "node": metric is a node-probe name (loop_lag_ms,
    repl_lag_events, store_errors), evaluated once per tick.
    mode "level" compares the current value; mode "growth" compares the
    change over the last ``window`` ticks (backlog growth).
    require_positive lists fields that must be > 0 for a breach to count
    (consumer stall = zero deliver rate WHILE depth and consumers > 0).
    """

    name: str
    scope: str                     # "queue" | "node"
    metric: str
    threshold: float
    op: str = ">"                  # ">" | "<"
    mode: str = "level"            # "level" | "growth"
    window: int = 5                # growth lookback, ticks
    for_ticks: int = 2             # consecutive breaches before firing
    clear_ticks: int = 3           # consecutive OKs before resolving
    severity: str = "warning"
    require_positive: tuple[str, ...] = field(default_factory=tuple)


def default_rules(
    *,
    backlog_growth: float = 100.0,
    backlog_window: int = 5,
    stall_ticks: int = 3,
    repl_lag: float = 1000.0,
    loop_lag_ms: float = 250.0,
    memory_stage: float = 3.5,
    control_floor_ticks: int = 300,
    drain_stuck_ticks: int = 2,
) -> list[AlertRule]:
    """The built-in rules, thresholds from chana.mq.alerts.*.

    memory-pressure alerts on the flow ladder's REFUSE stage (stage 4 >
    3.5) by default — throttling (stage 2) is routine overload shedding
    and would be noisy; refusing publishes is operator-actionable."""
    return [
        AlertRule(
            name="backlog-growth", scope="queue", metric="depth",
            mode="growth", window=backlog_window, threshold=backlog_growth,
            for_ticks=2, severity="warning"),
        AlertRule(
            name="consumer-stall", scope="queue", metric="deliver_rate",
            op="<", threshold=1e-9, for_ticks=stall_ticks,
            require_positive=("depth", "consumers"), severity="critical"),
        AlertRule(
            name="replication-lag", scope="node", metric="repl_lag_events",
            threshold=repl_lag, for_ticks=2, severity="warning"),
        AlertRule(
            name="loop-lag", scope="node", metric="loop_lag_ms",
            threshold=loop_lag_ms, for_ticks=2, severity="critical"),
        AlertRule(
            name="memory-pressure", scope="node", metric="memory_stage",
            threshold=memory_stage, for_ticks=2, severity="critical"),
        # predictive-control watchdog: a pre-armed throttle floor is
        # supposed to relax within a spike's horizon; one pinned for this
        # many consecutive ticks means the forecast is stuck pessimistic
        # or the relax path is broken. The default (5 min at 1 s ticks)
        # keeps it inert in short soaks — it exists for real deployments.
        AlertRule(
            name="control-prearm-stuck", scope="node",
            metric="control_floor", threshold=0.5,
            for_ticks=max(1, control_floor_ticks), severity="warning"),
        # a graceful drain past its evacuation budget: queues are pinned
        # (streams, local consumers) or every handoff attempt is failing —
        # the node will sit in `draining` forever without intervention
        AlertRule(
            name="drain-stuck", scope="node", metric="drain_overdue",
            threshold=0.5, for_ticks=max(1, drain_stuck_ticks),
            severity="critical"),
    ]


class AlertEngine:
    """Tick-driven evaluator with per-(rule, entity) hysteresis state."""

    HISTORY = 256  # retained fire/resolve events for /admin/alerts

    def __init__(self, rules: list[AlertRule]) -> None:
        self.rules = list(rules)
        for rule in self.rules:
            if rule.scope == "queue" and rule.metric not in QUEUE_FIELDS:
                raise ValueError(
                    f"rule {rule.name!r}: unknown queue metric {rule.metric!r}")
        # (rule name, entity key) -> consecutive breach ticks (pre-fire)
        self._breach: dict[tuple, int] = {}
        # (rule name, entity key) -> consecutive OK ticks (pre-resolve)
        self._ok: dict[tuple, int] = {}
        # (rule name, entity key) -> {rule, entity, value, since_tick, ...}
        self.firing: dict[tuple, dict] = {}
        self.history: deque = deque(maxlen=self.HISTORY)
        self.fired_total = 0
        self.resolved_total = 0
        # every rule name that ever fired (the soak asserts this exactly)
        self.fired_rules: set[str] = set()

    # -- evaluation --------------------------------------------------------

    def evaluate(
        self,
        tick: int,
        queue_keys: list,
        latest: np.ndarray,
        deltas_for: "callable",
        node_entity: str,
        node_probes: dict[str, float],
    ) -> list[dict]:
        """One tick. latest is the (E, F) QUEUE_FIELDS matrix aligned with
        queue_keys; deltas_for(window) returns the aligned growth matrix.
        Returns the tick's transition events ({event: fired|resolved, ...}),
        in deterministic (rule order, sorted entity) order."""
        events: list[dict] = []
        for rule in self.rules:
            if rule.scope == "node":
                value = float(node_probes.get(rule.metric, 0.0))
                breach = (value > rule.threshold if rule.op == ">"
                          else value < rule.threshold)
                self._step(rule, node_entity, breach, value, tick, events)
                continue
            if not queue_keys:
                breached_keys: dict = {}
            else:
                col = QUEUE_FIELDS.index(rule.metric)
                if rule.mode == "growth":
                    values = deltas_for(rule.window)[:, col]
                else:
                    values = latest[:, col]
                mask = (values > rule.threshold if rule.op == ">"
                        else values < rule.threshold)
                for fname in rule.require_positive:
                    mask &= latest[:, QUEUE_FIELDS.index(fname)] > 0
                breached_keys = {
                    queue_keys[i]: float(values[i])
                    for i in np.nonzero(mask)[0]
                }
            # step breached entities plus everything already tracked for
            # this rule (their streaks must advance toward resolve)
            tracked = {k for (r, k) in list(self._breach) if r == rule.name}
            tracked |= {k for (r, k) in list(self.firing) if r == rule.name}
            for key in sorted(set(breached_keys) | tracked):
                self._step(rule, key, key in breached_keys,
                           breached_keys.get(key, 0.0), tick, events)
        return events

    def _step(
        self, rule: AlertRule, entity, breach: bool, value: float,
        tick: int, events: list[dict],
    ) -> None:
        fkey = (rule.name, entity)
        if breach:
            self._ok.pop(fkey, None)
            if fkey in self.firing:
                self.firing[fkey]["value"] = value
                self.firing[fkey]["ticks"] = tick - self.firing[fkey]["since_tick"]
                return
            streak = self._breach.get(fkey, 0) + 1
            if streak >= rule.for_ticks:
                self._breach.pop(fkey, None)
                info = {
                    "rule": rule.name, "scope": rule.scope,
                    "entity": self._entity_str(entity),
                    "metric": rule.metric, "value": value,
                    "threshold": rule.threshold, "severity": rule.severity,
                    "since_tick": tick, "ticks": 0,
                }
                self.firing[fkey] = info
                self.fired_total += 1
                self.fired_rules.add(rule.name)
                events.append({"event": "fired", **info})
            else:
                self._breach[fkey] = streak
            return
        # not breaching
        self._breach.pop(fkey, None)
        if fkey in self.firing:
            ok = self._ok.get(fkey, 0) + 1
            if ok >= rule.clear_ticks:
                info = self.firing.pop(fkey)
                self._ok.pop(fkey, None)
                self.resolved_total += 1
                events.append({"event": "resolved", **info,
                               "resolved_tick": tick})
            else:
                self._ok[fkey] = ok

    @staticmethod
    def _entity_str(entity) -> str:
        if isinstance(entity, tuple):
            return "/".join(str(p) for p in entity)
        return str(entity)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        firing = sorted(
            self.firing.values(),
            key=lambda i: (i["rule"], i["entity"]))
        return {
            "rules": [
                {
                    "name": r.name, "scope": r.scope, "metric": r.metric,
                    "op": r.op, "mode": r.mode, "threshold": r.threshold,
                    "for_ticks": r.for_ticks, "clear_ticks": r.clear_ticks,
                    "severity": r.severity,
                }
                for r in self.rules
            ],
            "firing": firing,
            "fired_total": self.fired_total,
            "resolved_total": self.resolved_total,
            "fired_rules": sorted(self.fired_rules),
            "recent": list(self.history),
        }

    def record(self, events: list[dict]) -> None:
        self.history.extend(events)
