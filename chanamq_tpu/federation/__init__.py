"""Cross-cluster federation: mirrored streams, cursors, DLX and Tx.

A *federation link* connects two independent clusters (each its own
membership, store and WAL) the way Pulsar's geo-replication connects
regions: the local cluster ships **sealed stream segments** to a named
remote, mirrors **named-cursor commits** so a consumer group can fail
over and resume contiguously from its committed offset, forwards
**dead-letter publishes** whose target exchange is owned by the remote,
and stages **Tx publishes** on the link boundary so a committed
transaction arrives at the far side as one all-or-nothing batch (riding
the same ``tx_batch`` WAL scope PR 17 built for local commits).

Transport is the PR 3 length-prefixed binary framing: segment blobs,
Tx batches and DLX forwards ride the data-plane kinds (``KIND_DREQUEST``
/ ``KIND_DRESPONSE``) through a :class:`~..cluster.dataplane.DataStream`
whose ``inflight`` semaphore is the per-link in-flight window; control
traffic (handshake, resume, cursor mirror) uses the table-codec RPC
kinds on the same federation listener. Segment reads on the shipping
side go through ``store.select_stream_segment`` — the PR 8 tiered-offload
path — so cold segments rehydrate transparently from the tier sidecar
(CRC-checked there) and are CRC32-checked again on the wire.

Resumability: the receiving side is the source of truth. ``fed.resume``
returns the mirror's ``next_offset`` per queue; the shipper ships only
from there, and any gap/duplicate race is settled by the receiver
(duplicates ack idempotently, gaps answer ``gap:<next>`` so the shipper
resyncs). A severed link therefore re-converges from whatever prefix
arrived, never double-applying and never skipping.

Observability follows the house pattern: ``federation_*`` counters in
the metrics registry, per-link ``chanamq_federation_link_lag`` gauges on
/metrics, ``federation.link.{up,down,resumed}`` and
``federation.cursor.mirrored`` events on the bus (plus a per-service
bounded transition log the soaks compare byte-for-byte), a
``federation-lag`` SLI feeding per-link SLO specs, and chaos seams
``fed.connect`` / ``fed.ship`` for deterministic fault injection.
"""

from __future__ import annotations

import json
from typing import Optional

from .link import FederationLink  # noqa: F401
from .service import FederationService  # noqa: F401


def links_from_json(raw: str) -> list[dict]:
    """Parse ``chana.mq.federation.links``: a JSON array of link specs
    (``name``, ``host``, ``port`` required; ``vhost`` defaults to "/",
    ``queues`` and ``exchanges`` to empty, ``window`` to the service
    default). Raises ValueError on garbage — a broken link spec should
    fail boot loudly, not ship nothing silently."""
    if not raw or not raw.strip():
        return []
    specs = json.loads(raw)
    if not isinstance(specs, list):
        raise ValueError("federation.links must be a JSON array")
    out = []
    for spec in specs:
        if not isinstance(spec, dict):
            raise ValueError(f"link spec must be an object: {spec!r}")
        for key in ("name", "host", "port"):
            if key not in spec:
                raise ValueError(f"link spec missing {key!r}: {spec!r}")
        out.append(spec)
    return out


async def enable_from_config(config, broker) -> Optional[FederationService]:
    """Boot-time wiring (``chana.mq.federation.enabled``): start the
    federation listener, build the configured links, hang the service off
    ``broker.federation``. Returns the started service (run_node stops it
    in the shutdown path)."""
    if not config.bool("chana.mq.federation.enabled"):
        return None
    raw_links = config.get("chana.mq.federation.links")
    if isinstance(raw_links, str):
        links = links_from_json(raw_links)  # env/JSON-file string form
    else:
        links = list(raw_links or [])       # already-parsed list form
    service = FederationService(
        broker,
        node_name=str(config.get("chana.mq.cluster.node-name") or ""),
        interface=config.str("chana.mq.federation.interface") or "127.0.0.1",
        port=config.int("chana.mq.federation.port") or 0,
        window=config.int("chana.mq.federation.window") or 4,
        retry_s=config.duration_s("chana.mq.federation.retry") or 0.5,
        idle_s=config.duration_s("chana.mq.federation.idle-tick") or 0.2,
        links=links,
        auth_token=config.str("chana.mq.federation.auth-token") or "",
    )
    await service.start()
    return service
