#!/usr/bin/env python3
"""Manual smoke consumer — the rebuild's analogue of the reference's
SimpleConsumer (chana-mq-test .../SimpleConsumer.scala:9-68): subscribe to
test_queue with autoAck and print deliveries for 20 seconds.

Usage: python examples/simple_consumer.py [host] [port]
"""

import asyncio
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from chanamq_tpu.client import AMQPClient

QUEUE = "test_queue"
LIFETIME_S = 20


async def main() -> None:
    host = sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1"
    port = int(sys.argv[2]) if len(sys.argv) > 2 else 5672
    conn = await AMQPClient.connect(host, port)
    ch = await conn.channel()
    print("going to consume...")

    def on_message(msg) -> None:
        print(f"Got {msg.body.decode(errors='replace')} "
              f"(tag={msg.delivery_tag}, exchange={msg.exchange!r}, "
              f"routing_key={msg.routing_key!r})")

    await ch.basic_consume(QUEUE, on_message, no_ack=True)
    await asyncio.sleep(LIFETIME_S)
    print("closing ...")
    await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
