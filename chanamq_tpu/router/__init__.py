"""Data-parallel tensorized router (chana.mq.router.*).

``compile`` turns one exchange's binding table into tokenized match
matrices + queue bitmask rows and evaluates whole publish batches in one
kernel call (jax.jit or numpy). ``engine.TensorRouter`` owns the compiled
snapshots, the incremental-recompile/generation machinery, and the
deferred-flush entry point the broker publishes through.
"""

from .compile import CompiledExchange, Uncompilable, compile_exchange, route_batch
from .engine import TensorRouter

__all__ = ["CompiledExchange", "Uncompilable", "compile_exchange",
           "route_batch", "TensorRouter"]
