"""Broker-wide memory accounting and overload protection.

One `MemoryAccountant` tracks the real resident costs of the broker
(queue body bytes, parked publishes, connection out-buffers, WAL
memtable, cluster data-plane in-flight, stream sealed-blob cache, plus
deterministic chaos inflation) and actuates a graceful-degradation
ladder, mildest first:

  stage 1 (page)     — aggressively page message bodies to the store
  stage 2 (throttle) — per-connection publish credit, channel.flow,
                       paused socket reads (the memory gate)
  stage 3 (cluster)  — shrink data-plane credit windows / stall
                       push_many replies so remote publishers slow down
  stage 4 (refuse)   — refuse new publishes with PRECONDITION_FAILED
                       while consumers keep draining

The reference broker had none of this (its backpressure was
akka-streams demand + TCP, SURVEY.md §7.3); the shape here follows the
Pulsar paper's position that brokers survive multi-tenant load only
when backpressure and load shedding are first-class.
"""

from .accountant import (
    MemoryAccountant,
    STAGE_CLUSTER,
    STAGE_NAMES,
    STAGE_NORMAL,
    STAGE_PAGE,
    STAGE_REFUSE,
    STAGE_THROTTLE,
)

__all__ = [
    "MemoryAccountant",
    "STAGE_NAMES",
    "STAGE_NORMAL",
    "STAGE_PAGE",
    "STAGE_THROTTLE",
    "STAGE_CLUSTER",
    "STAGE_REFUSE",
]
