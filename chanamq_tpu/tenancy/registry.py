"""Tenant registry: named principals owning vhosts, with quotas and ACLs.

A tenant is a named principal that owns one or more vhosts and carries a
:class:`TenantQuota`. Enforcement deliberately reuses machinery that
already exists instead of adding hot-path branches:

- **publish rate** is a per-tenant token bucket (refilled on the broker
  sweep tick, deterministically, at ``publish-rate`` bytes/sec up to
  ``publish-burst``). When the bucket empties the tenant's connections
  flip their ``_throttled`` flag and publishes park at the SAME hold gate
  the memory ladder uses; while parked, ``_spend_tenant_credit`` draws the
  PR 9 per-connection publish-credit grant from whatever tokens the bucket
  has re-accrued, so drain resumes at exactly the quota rate.
- **memory share** is a per-tenant stage floor on the flow ladder: when a
  tenant's resident queue bytes exceed ``memory-share`` x the broker's
  memory high watermark, the tenant is pinned at ``STAGE_THROTTLE`` (its
  publishers hold) until it drains below the exit ratio — the same
  enter/exit hysteresis shape the accountant itself uses.
- **connection/channel/queue/binding caps** are checked at the existing
  declare/open mutation sites (Connection.Open, Channel.Open,
  Broker.declare_queue, Broker.bind_queue); the checks return error text
  and the call sites raise the protocol-appropriate refusal.

Auth: each tenant may declare a ``users`` table (user -> password) and an
``acls`` table (user -> vhost -> subset of configure/write/read,
RabbitMQ's permission triple). The registry merges tenant users into the
server-wide SASL PLAIN table and derives vhost allowlists, so declaring a
tenant at runtime (``POST /admin/tenants``) takes effect on the next
handshake without restarting listeners.

Determinism: every gate transition appends to ``decision_log`` with only
deterministic fields (tenant, reason, token/byte counts — no wall clock),
so two same-seed soak runs produce byte-identical logs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..flow.accountant import STAGE_NORMAL, STAGE_THROTTLE
from ..utils.metrics import Histogram

ACL_PERMS = ("configure", "write", "read")

#: hysteresis: a memory-share floor lifts once the tenant drains to this
#: fraction of its share (mirrors the accountant's exit = 0.8 * enter)
MEMORY_EXIT_RATIO = 0.8

_QUOTA_KEYS = frozenset({
    "max-connections", "max-channels", "max-queues", "max-bindings",
    "memory-share", "publish-rate", "publish-burst",
})


class TenancyError(ValueError):
    """Invalid tenant/quota spec: 400 at the admin surface, ConfigError at
    boot. Deliberately not a BrokerError — the registry must stay
    importable without the broker module."""


def _int_field(raw: dict, key: str) -> int:
    value = raw.get(key, 0)
    if isinstance(value, bool) or not isinstance(value, int) or value < 0:
        raise TenancyError(f"quota {key!r} must be a non-negative integer")
    return value


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant limits; 0 (or 0.0) disables the corresponding cap."""

    max_connections: int = 0
    max_channels: int = 0
    max_queues: int = 0
    max_bindings: int = 0
    memory_share: float = 0.0   # fraction of the memory high watermark
    publish_rate: int = 0       # token-bucket refill, bytes/sec
    publish_burst: int = 0      # bucket capacity; default 2x publish-rate

    @classmethod
    def from_spec(cls, raw: Optional[dict]) -> "TenantQuota":
        if raw is None:
            return cls()
        if not isinstance(raw, dict):
            raise TenancyError("quota must be a JSON object")
        unknown = sorted(set(raw) - _QUOTA_KEYS)
        if unknown:
            raise TenancyError(
                f"unknown quota keys {unknown} (have {sorted(_QUOTA_KEYS)})")
        share = raw.get("memory-share", 0.0)
        if isinstance(share, bool) or not isinstance(share, (int, float)) \
                or not 0.0 <= float(share) <= 1.0:
            raise TenancyError("quota 'memory-share' must be in [0, 1]")
        rate = _int_field(raw, "publish-rate")
        burst = _int_field(raw, "publish-burst")
        if burst and not rate:
            raise TenancyError(
                "quota 'publish-burst' requires 'publish-rate'")
        return cls(
            max_connections=_int_field(raw, "max-connections"),
            max_channels=_int_field(raw, "max-channels"),
            max_queues=_int_field(raw, "max-queues"),
            max_bindings=_int_field(raw, "max-bindings"),
            memory_share=float(share),
            publish_rate=rate,
            publish_burst=burst or 2 * rate,
        )

    def as_dict(self) -> dict:
        return {
            "max-connections": self.max_connections,
            "max-channels": self.max_channels,
            "max-queues": self.max_queues,
            "max-bindings": self.max_bindings,
            "memory-share": self.memory_share,
            "publish-rate": self.publish_rate,
            "publish-burst": self.publish_burst,
        }


def _parse_acls(raw, vhosts: tuple, users: dict) -> dict:
    """user -> vhost -> frozenset(perms). Validated fail-closed: an ACL
    naming an unknown user or a vhost outside the tenant would be silently
    unenforceable, so both are spec errors."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        raise TenancyError("acls must map user names to vhost permission maps")
    acls: dict = {}
    for user, by_vhost in raw.items():
        if not isinstance(user, str) or user not in users:
            raise TenancyError(
                f"acls name unknown user {user!r} (declare it under users)")
        if not isinstance(by_vhost, dict):
            raise TenancyError(
                f"acls[{user!r}] must map vhosts to permission lists")
        acls[user] = {}
        for vhost, perms in by_vhost.items():
            if vhost not in vhosts:
                raise TenancyError(
                    f"acls[{user!r}] names vhost {vhost!r} outside the tenant")
            if not isinstance(perms, list) or not all(
                    p in ACL_PERMS for p in perms):
                raise TenancyError(
                    f"acls[{user!r}][{vhost!r}] must be a subset of "
                    f"{list(ACL_PERMS)}")
            acls[user][vhost] = frozenset(perms)
    return acls


class Tenant:
    """One named principal: owned vhosts, auth tables, quota, live state."""

    def __init__(self, registry: "TenantRegistry", name: str,
                 vhosts: tuple, users: dict, acls: dict,
                 quota: TenantQuota) -> None:
        self.registry = registry
        self.name = name
        self.vhosts = vhosts
        self.users = users
        self.acls = acls
        self.quota = quota
        # live connections (AMQPConnection objects); counters for closed
        # connections fold into the *_folded totals at teardown so the
        # per-tenant series stay monotonic
        self.conns: set = set()
        self.published_folded = 0
        self.delivered_folded = 0
        self.refused = 0       # ACL + quota publish refusals
        self.throttles = 0     # gate-close transitions
        # publish-rate token bucket (floats: refill is rate * dt)
        self.tokens = float(quota.publish_burst)
        self.rate_gated = False
        self.memory_gated = False
        self.resident_bytes = 0  # sampled each registry tick
        # per-tenant publish->deliver histogram, allocated only when a
        # delivery-latency SLO targets this tenant (see attach_latency) —
        # a plain quota tenant pays nothing on the delivery path
        self.latency_hist: Optional[Histogram] = None

    # -- identity / auth ---------------------------------------------------

    def acl_for(self, username: Optional[str],
                vhost: str) -> tuple[bool, bool, bool]:
        """(configure, write, read) for one user on one vhost. ACLs are
        opt-in per user (like the vhost allowlists): a user absent from
        the table is unrestricted; a listed user gets exactly the declared
        perms (missing vhost entry -> none)."""
        if not self.acls or username is None or username not in self.acls:
            return (True, True, True)
        perms = self.acls[username].get(vhost, frozenset())
        return ("configure" in perms, "write" in perms, "read" in perms)

    # -- derived counters --------------------------------------------------

    def published_total(self) -> int:
        return self.published_folded + sum(
            c.published_msgs for c in self.conns)

    def delivered_total(self) -> int:
        return self.delivered_folded + sum(
            c.delivered_msgs for c in self.conns)

    # -- publish-rate token bucket ----------------------------------------

    @property
    def rated(self) -> bool:
        return self.quota.publish_rate > 0

    @property
    def gated(self) -> bool:
        return self.rate_gated or self.memory_gated

    @property
    def floor(self) -> int:
        """The tenant's stage floor on the flow ladder: pinned at
        STAGE_THROTTLE while its memory share is breached (PR 10's floor
        mechanism, scoped to one tenant's connections)."""
        return STAGE_THROTTLE if self.memory_gated else STAGE_NORMAL

    def spend(self, cost: int) -> None:
        """Spend bucket tokens for one executed publish (called from the
        connection publish paths only when ``rated``)."""
        self.tokens -= cost
        if self.tokens <= 0.0 and not self.rate_gated:
            self.rate_gated = True
            self.registry._apply_gate(self, "publish-rate")

    def take_credit(self, cap: int) -> int:
        """Feed the per-connection publish-credit grant from the bucket
        while the tenant gate is closed: a gated connection may draw up to
        the broker's flow grant from whatever tokens have re-accrued."""
        take = min(int(cap or 0), int(self.tokens))
        if take <= 0:
            return 0
        self.tokens -= take
        return take

    def attach_latency(self) -> Histogram:
        if self.latency_hist is None:
            self.latency_hist = Histogram()
        return self.latency_hist

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "vhosts": list(self.vhosts),
            "users": sorted(self.users),
            "acls": {
                user: {vh: sorted(perms) for vh, perms in by_vhost.items()}
                for user, by_vhost in self.acls.items()
            },
            "quota": self.quota.as_dict(),
            "connections": len(self.conns),
            "channels": sum(len(c.channels) for c in self.conns),
            "queues": self.registry.queue_count(self),
            "bindings": self.registry.binding_count(self),
            "resident_bytes": self.resident_bytes,
            "tokens": int(self.tokens),
            "gated": self.gated,
            "floor": self.floor,
            "published": self.published_total(),
            "delivered": self.delivered_total(),
            "refused": self.refused,
            "throttles": self.throttles,
        }


class TenantRegistry:
    """All tenants on one node, plus the vhost/user reverse maps the
    enforcement seams look identities up through."""

    def __init__(self, broker) -> None:
        self.broker = broker
        self.tenants: dict[str, Tenant] = {}
        self.by_vhost: dict[str, Tenant] = {}
        self.by_user: dict[str, Tenant] = {}
        # deterministic gate-transition ledger (see module docstring)
        self.decision_log: list[dict] = []
        self.ticks = 0

    # -- definition --------------------------------------------------------

    def define(self, name: str, spec: dict) -> Tenant:
        """Create or replace one tenant from a spec dict (config file,
        env JSON, or POST /admin/tenants). Raises TenancyError on any
        invalid shape; a replacement keeps the old tenant's live
        connections and counters but adopts the new quota/auth tables."""
        if not isinstance(name, str) or not name:
            raise TenancyError("tenant name must be a non-empty string")
        if not isinstance(spec, dict):
            raise TenancyError(f"tenant {name!r}: spec must be a JSON object")
        unknown = sorted(set(spec) - {"vhosts", "users", "acls", "quota"})
        if unknown:
            raise TenancyError(f"tenant {name!r}: unknown keys {unknown}")
        vhosts_raw = spec.get("vhosts")
        if not isinstance(vhosts_raw, list) or not vhosts_raw or not all(
                isinstance(v, str) and v for v in vhosts_raw):
            raise TenancyError(
                f"tenant {name!r}: vhosts must be a non-empty string list")
        vhosts = tuple(dict.fromkeys(vhosts_raw))
        users_raw = spec.get("users") or {}
        if not isinstance(users_raw, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in users_raw.items()):
            raise TenancyError(
                f"tenant {name!r}: users must map user names to passwords")
        acls = _parse_acls(spec.get("acls"), vhosts, users_raw)
        quota = TenantQuota.from_spec(spec.get("quota"))
        # cross-tenant uniqueness: a vhost or user claimed by two tenants
        # would make identity resolution ambiguous
        for vhost in vhosts:
            other = self.by_vhost.get(vhost)
            if other is not None and other.name != name:
                raise TenancyError(
                    f"vhost {vhost!r} already owned by tenant {other.name!r}")
        for user in users_raw:
            other = self.by_user.get(user)
            if other is not None and other.name != name:
                raise TenancyError(
                    f"user {user!r} already declared by tenant {other.name!r}")
        existing = self.tenants.get(name)
        if existing is not None:
            self._unindex(existing)
            existing.vhosts = vhosts
            existing.users = dict(users_raw)
            existing.acls = acls
            if existing.quota != quota:
                existing.quota = quota
                existing.tokens = min(
                    existing.tokens, float(quota.publish_burst)) \
                    if quota.publish_rate else float(quota.publish_burst)
            tenant = existing
        else:
            tenant = Tenant(self, name, vhosts, dict(users_raw), acls, quota)
            self.tenants[name] = tenant
        self._index(tenant)
        return tenant

    def remove(self, name: str) -> bool:
        tenant = self.tenants.pop(name, None)
        if tenant is None:
            return False
        self._unindex(tenant)
        # lift any closed gate so surviving connections (now tenantless
        # for quota purposes) don't stay parked forever
        if tenant.gated:
            tenant.rate_gated = tenant.memory_gated = False
            for conn in list(tenant.conns):
                conn.set_tenant_gate(False)
        for conn in list(tenant.conns):
            conn.detach_tenant()
        return True

    def _index(self, tenant: Tenant) -> None:
        for vhost in tenant.vhosts:
            self.by_vhost[vhost] = tenant
        for user in tenant.users:
            self.by_user[user] = tenant

    def _unindex(self, tenant: Tenant) -> None:
        for vhost in tenant.vhosts:
            if self.by_vhost.get(vhost) is tenant:
                del self.by_vhost[vhost]
        for user in tenant.users:
            if self.by_user.get(user) is tenant:
                del self.by_user[user]

    # -- identity ----------------------------------------------------------

    def tenant_of_vhost(self, vhost: Optional[str]) -> Optional[str]:
        tenant = self.by_vhost.get(vhost) if vhost else None
        return tenant.name if tenant is not None else None

    # -- auth views (consumed by the SASL / Connection.Open seams) ---------

    def auth_users(self, base: Optional[dict]) -> Optional[dict]:
        """The effective SASL PLAIN table: server-wide users merged with
        every tenant's. None (open access, reference parity) only when
        neither declares any user."""
        merged = dict(base) if base else {}
        for tenant in self.tenants.values():
            merged.update(tenant.users)
        return merged or None

    def auth_permissions(self, base: Optional[dict]) -> Optional[dict]:
        """Effective vhost allowlists: tenant users are confined to their
        tenant's vhosts (on top of any server-wide allowlists)."""
        merged = dict(base) if base else {}
        for tenant in self.tenants.values():
            for user in tenant.users:
                merged[user] = list(tenant.vhosts)
        return merged or None

    # -- quota checks (error text or None; call sites raise) ---------------

    def connection_refusal(self, vhost: str) -> Optional[str]:
        tenant = self.by_vhost.get(vhost)
        if tenant is None:
            return None
        cap = tenant.quota.max_connections
        if cap and len(tenant.conns) >= cap:
            self._count_refusal(tenant)
            return (f"tenant '{tenant.name}': connection quota "
                    f"({cap}) exceeded")
        return None

    def channel_refusal(self, tenant: Tenant) -> Optional[str]:
        cap = tenant.quota.max_channels
        if cap and sum(len(c.channels) for c in tenant.conns) >= cap:
            self._count_refusal(tenant)
            return f"tenant '{tenant.name}': channel quota ({cap}) exceeded"
        return None

    def queue_refusal(self, vhost: str) -> Optional[str]:
        tenant = self.by_vhost.get(vhost)
        if tenant is None:
            return None
        cap = tenant.quota.max_queues
        if cap and self.queue_count(tenant) >= cap:
            self._count_refusal(tenant)
            return f"tenant '{tenant.name}': queue quota ({cap}) exceeded"
        return None

    def binding_refusal(self, vhost: str) -> Optional[str]:
        tenant = self.by_vhost.get(vhost)
        if tenant is None:
            return None
        cap = tenant.quota.max_bindings
        if cap and self.binding_count(tenant) >= cap:
            self._count_refusal(tenant)
            return f"tenant '{tenant.name}': binding quota ({cap}) exceeded"
        return None

    def _count_refusal(self, tenant: Tenant) -> None:
        tenant.refused += 1
        self.broker.metrics.tenancy_quota_refusals_total += 1

    # live counts walk the real structures instead of shadow counters:
    # declares/deletes/vhost drops can't drift a number that is recomputed
    def queue_count(self, tenant: Tenant) -> int:
        vhosts = self.broker.vhosts
        return sum(
            len(vhosts[v].queues) for v in tenant.vhosts if v in vhosts)

    def binding_count(self, tenant: Tenant) -> int:
        total = 0
        vhosts = self.broker.vhosts
        for v in tenant.vhosts:
            vhost = vhosts.get(v)
            if vhost is None:
                continue
            for exchange in vhost.exchanges.values():
                total += len(exchange.matcher.bindings())
                if exchange.ex_matcher is not None:
                    total += len(exchange.ex_matcher.bindings())
        return total

    def tenant_resident_bytes(self, tenant: Tenant) -> int:
        vhosts = self.broker.vhosts
        return sum(
            q.ready_bytes
            for v in tenant.vhosts if v in vhosts
            for q in vhosts[v].queues.values())

    # -- gate machinery ----------------------------------------------------

    def _apply_gate(self, tenant: Tenant, reason: str) -> None:
        """A tenant gate closed (bucket empty or memory share breached):
        flip the tenant's connections onto the hold path and ledger it."""
        tenant.throttles += 1
        self.broker.metrics.tenancy_throttles_total += 1
        for conn in list(tenant.conns):
            conn.set_tenant_gate(True)
        self._log("throttle", tenant, reason)

    def _lift_gate(self, tenant: Tenant, reason: str) -> None:
        self.broker.metrics.tenancy_resumes_total += 1
        for conn in list(tenant.conns):
            conn.set_tenant_gate(False)
        self._log("resume", tenant, reason)

    def _log(self, decision: str, tenant: Tenant, reason: str) -> None:
        entry = {
            "decision": decision, "tenant": tenant.name, "reason": reason,
            "tick": self.ticks, "tokens": int(tenant.tokens),
            "resident": tenant.resident_bytes, "floor": tenant.floor,
            "published": tenant.published_total(),
        }
        self.decision_log.append(entry)
        from .. import events

        bus = events.ACTIVE
        if bus is not None:
            bus.emit(f"tenant.{decision}.{tenant.name}",
                     {"tenant": tenant.name, **entry})

    def tick(self, dt: float) -> None:
        """One deterministic registry tick (driven by the broker sweep, or
        by a soak harness): refill token buckets, sample per-tenant
        resident bytes, move the memory-share floors with hysteresis, and
        lift rate gates whose buckets re-accrued."""
        self.ticks += 1
        high = self.broker.memory_high_watermark
        for name in sorted(self.tenants):
            tenant = self.tenants[name]
            quota = tenant.quota
            tenant.resident_bytes = self.tenant_resident_bytes(tenant)
            was_gated = tenant.gated
            if quota.publish_rate:
                tenant.tokens = min(
                    float(quota.publish_burst),
                    tenant.tokens + quota.publish_rate * dt)
                if tenant.rate_gated and tenant.tokens > 0.0:
                    tenant.rate_gated = False
            if quota.memory_share and high:
                limit = int(quota.memory_share * high)
                if (not tenant.memory_gated
                        and tenant.resident_bytes > limit):
                    tenant.memory_gated = True
                elif (tenant.memory_gated
                      and tenant.resident_bytes
                      <= int(limit * MEMORY_EXIT_RATIO)):
                    tenant.memory_gated = False
            if tenant.gated and not was_gated:
                tenant.throttles += 1
                self.broker.metrics.tenancy_throttles_total += 1
                for conn in list(tenant.conns):
                    conn.set_tenant_gate(True)
                self._log("throttle", tenant, "memory-share")
            elif was_gated and not tenant.gated:
                self._lift_gate(
                    tenant, "refill" if quota.publish_rate else "drain")

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "tenants": [
                self.tenants[name].snapshot()
                for name in sorted(self.tenants)
            ],
            "count": len(self.tenants),
            "ticks": self.ticks,
            "decisions": len(self.decision_log),
        }
