"""Broker-metrics forecaster: a small causal transformer in pure JAX.

Input: a window of per-tick broker telemetry vectors
(features: enqueue rate, dequeue rate, queue depth, unacked count, consumer
count, publish bytes, deliver bytes, confirm rate — sampled from
chanamq_tpu.utils.metrics by chanamq_tpu.models.telemetry). Output: the
forecast telemetry vector for the next tick. Used for backlog/capacity
prediction; never on the message path. chanamq_tpu.models.service runs the
live loop: sample -> ring -> off-path train/predict -> /admin/forecast.

Design notes (TPU):
- all matmuls in bfloat16 with float32 accumulation (MXU native);
- static shapes everywhere, no data-dependent control flow -> one XLA trace;
- dims chosen as multiples of 128 lanes where it matters (d_model, d_ff);
- params are a flat pytree of named arrays so chanamq_tpu.parallel can map
  each leaf to a NamedSharding over a (dp, tp) mesh and let GSPMD insert the
  collectives (the scaling-book recipe).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ForecasterConfig:
    n_features: int = 8
    seq_len: int = 64
    d_model: int = 256
    n_heads: int = 4
    d_ff: int = 1024
    n_layers: int = 4
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


Params = dict[str, jnp.ndarray]


def init_params(rng: jax.Array, cfg: ForecasterConfig) -> Params:
    """Flat {name: array} param tree (names carry layer index)."""
    keys = iter(jax.random.split(rng, 4 + cfg.n_layers * 6))
    scale = lambda fan_in: 1.0 / math.sqrt(fan_in)
    p: Params = {
        "embed/kernel": jax.random.normal(
            next(keys), (cfg.n_features, cfg.d_model)) * scale(cfg.n_features),
        "embed/bias": jnp.zeros((cfg.d_model,)),
        "pos": jax.random.normal(
            next(keys), (cfg.seq_len, cfg.d_model)) * 0.02,
        "out/kernel": jax.random.normal(
            next(keys), (cfg.d_model, cfg.n_features)) * scale(cfg.d_model),
        "out/bias": jnp.zeros((cfg.n_features,)),
    }
    for layer in range(cfg.n_layers):
        pre = f"layer{layer}"
        p[f"{pre}/ln1/scale"] = jnp.ones((cfg.d_model,))
        p[f"{pre}/ln2/scale"] = jnp.ones((cfg.d_model,))
        p[f"{pre}/attn/qkv"] = jax.random.normal(
            next(keys), (cfg.d_model, 3 * cfg.d_model)) * scale(cfg.d_model)
        p[f"{pre}/attn/proj"] = jax.random.normal(
            next(keys), (cfg.d_model, cfg.d_model)) * scale(cfg.d_model)
        p[f"{pre}/mlp/w1"] = jax.random.normal(
            next(keys), (cfg.d_model, cfg.d_ff)) * scale(cfg.d_model)
        p[f"{pre}/mlp/w2"] = jax.random.normal(
            next(keys), (cfg.d_ff, cfg.d_model)) * scale(cfg.d_ff)
    return p


def _layernorm(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _attention(x: jnp.ndarray, qkv: jnp.ndarray, proj: jnp.ndarray,
               cfg: ForecasterConfig) -> jnp.ndarray:
    b, t, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    fused = jnp.einsum("btd,de->bte", x, qkv.astype(x.dtype))
    q, k, v = jnp.split(fused, 3, axis=-1)
    q = q.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(hd)
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(causal, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", weights, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    return jnp.einsum("btd,de->bte", out, proj.astype(x.dtype))


def forward(params: Params, x: jnp.ndarray, cfg: ForecasterConfig) -> jnp.ndarray:
    """x: [batch, seq_len, n_features] float32 -> forecast [batch, n_features]."""
    h = x.astype(cfg.dtype)
    h = jnp.einsum("btf,fd->btd", h, params["embed/kernel"].astype(cfg.dtype))
    h = h + params["embed/bias"].astype(cfg.dtype)
    h = h + params["pos"].astype(cfg.dtype)[None, : x.shape[1]]
    for layer in range(cfg.n_layers):
        pre = f"layer{layer}"
        a = _layernorm(h, params[f"{pre}/ln1/scale"])
        h = h + _attention(a, params[f"{pre}/attn/qkv"],
                           params[f"{pre}/attn/proj"], cfg)
        m = _layernorm(h, params[f"{pre}/ln2/scale"])
        m = jnp.einsum("btd,df->btf", m, params[f"{pre}/mlp/w1"].astype(cfg.dtype))
        m = jax.nn.gelu(m)
        m = jnp.einsum("btf,fd->btd", m, params[f"{pre}/mlp/w2"].astype(cfg.dtype))
        h = h + m
    last = h[:, -1, :].astype(jnp.float32)
    return last @ params["out/kernel"] + params["out/bias"]


def loss_fn(params: Params, batch: tuple[jnp.ndarray, jnp.ndarray],
            cfg: ForecasterConfig) -> jnp.ndarray:
    x, y = batch
    pred = forward(params, x, cfg)
    return jnp.mean((pred - y) ** 2)


def make_train_step(
    cfg: ForecasterConfig, lr: float = 1e-3,
    clip_norm: Optional[float] = 1.0,
) -> Callable:
    """SGD-with-momentum train step (pure jax, optax-free so the hot path is
    a single fused XLA program). Returns step(params, opt_state, batch) ->
    (params, opt_state, loss). Gradients are clipped by global norm: live
    telemetry has regime switches (idle -> flood) whose spiky loss surface
    diverges unclipped SGD (observed: NaN within 60 steps on real traffic)."""

    def step(params: Params, momentum: Params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
        if clip_norm is not None:
            global_sq = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads))
            scale = jnp.minimum(
                1.0, clip_norm * jax.lax.rsqrt(global_sq + 1e-12))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        new_momentum = jax.tree_util.tree_map(
            lambda m, g: 0.9 * m + g, momentum, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, m: p - lr * m, params, new_momentum)
        return new_params, new_momentum, loss

    return step


def init_momentum(params: Params) -> Params:
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def synthetic_batch(rng: jax.Array, cfg: ForecasterConfig, batch: int):
    """Synthetic telemetry: noisy seasonal rates (for tests and dryruns)."""
    t = jnp.arange(cfg.seq_len + 1, dtype=jnp.float32)
    phase = jax.random.uniform(rng, (batch, 1, cfg.n_features)) * 2 * jnp.pi
    freq = 0.1 + jax.random.uniform(rng, (batch, 1, cfg.n_features)) * 0.3
    series = jnp.sin(t[None, :, None] * freq + phase) + 1.5
    noise = jax.random.normal(rng, series.shape) * 0.05
    series = series + noise
    return series[:, :-1, :], series[:, -1, :]
