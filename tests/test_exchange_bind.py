"""Exchange-to-exchange bindings (exchange.bind / exchange.unbind).

EXCEEDS the reference, which stubs Exchange.Bind/Unbind with TODO logs
(chana-mq-server .../engine/FrameStage.scala:1023-1027). Semantics follow
RabbitMQ's e2e extension: messages accepted by the source exchange flow to
bound destination exchanges, each hop re-matching the ORIGINAL routing
key/headers; the traversal is cycle-safe and a queue reachable via multiple
paths receives exactly one copy.
"""

import asyncio

import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def drain(ch, queue, n, timeout=2.0):
    out = []
    deadline = asyncio.get_event_loop().time() + timeout
    while len(out) < n and asyncio.get_event_loop().time() < deadline:
        msg = await ch.basic_get(queue, no_ack=True)
        if msg is None:
            await asyncio.sleep(0.02)
            continue
        out.append(msg)
    return out


async def test_capability_advertised(client):
    caps = client.server_properties["capabilities"]
    assert caps["exchange_exchange_bindings"] is True


async def test_direct_to_fanout_chain(client):
    ch = await client.channel()
    await ch.exchange_declare("src", "direct")
    await ch.exchange_declare("fan", "fanout")
    await ch.queue_declare("q_src")
    await ch.queue_declare("q_fan1")
    await ch.queue_declare("q_fan2")
    await ch.queue_bind("q_src", "src", "k")
    await ch.queue_bind("q_fan1", "fan", "")
    await ch.queue_bind("q_fan2", "fan", "")
    await ch.exchange_bind("fan", "src", "k")

    ch.basic_publish(b"hop", exchange="src", routing_key="k")
    assert [m.body for m in await drain(ch, "q_src", 1)] == [b"hop"]
    assert [m.body for m in await drain(ch, "q_fan1", 1)] == [b"hop"]
    assert [m.body for m in await drain(ch, "q_fan2", 1)] == [b"hop"]

    # a key the binding doesn't cover goes nowhere downstream
    ch.basic_publish(b"miss", exchange="src", routing_key="other")
    await asyncio.sleep(0.05)
    assert await ch.basic_get("q_fan1", no_ack=True) is None


async def test_queue_reached_via_two_paths_gets_one_copy(client):
    ch = await client.channel()
    await ch.exchange_declare("top", "fanout")
    await ch.exchange_declare("mid_a", "fanout")
    await ch.exchange_declare("mid_b", "fanout")
    await ch.queue_declare("q_diamond")
    await ch.exchange_bind("mid_a", "top", "")
    await ch.exchange_bind("mid_b", "top", "")
    await ch.queue_bind("q_diamond", "mid_a", "")
    await ch.queue_bind("q_diamond", "mid_b", "")

    ch.basic_publish(b"once", exchange="top", routing_key="")
    got = await drain(ch, "q_diamond", 1)
    assert [m.body for m in got] == [b"once"]
    await asyncio.sleep(0.05)
    assert await ch.basic_get("q_diamond", no_ack=True) is None


async def test_cycle_is_refused(client):
    """A bind that would close a directed cycle is refused at declare
    time with 406 PRECONDITION_FAILED (semantics/graph.py): the runtime
    walk is cycle-safe, but a cyclic graph blocks closure flattening and
    is almost certainly a client bug. The refusal must leave the
    existing acyclic binding fully functional."""
    ch = await client.channel()
    await ch.exchange_declare("loop_a", "fanout")
    await ch.exchange_declare("loop_b", "fanout")
    await ch.queue_declare("q_a")
    await ch.queue_declare("q_b")
    await ch.exchange_bind("loop_b", "loop_a", "")
    with pytest.raises(ChannelClosedError) as exc:
        await ch.exchange_bind("loop_a", "loop_b", "")  # closes the cycle
    assert "406" in str(exc.value)

    # the refusing channel closed; the surviving topology still routes
    ch2 = await client.channel()
    await ch2.queue_bind("q_a", "loop_a", "")
    await ch2.queue_bind("q_b", "loop_b", "")
    ch2.basic_publish(b"ring", exchange="loop_a", routing_key="")
    assert [m.body for m in await drain(ch2, "q_a", 1)] == [b"ring"]
    assert [m.body for m in await drain(ch2, "q_b", 1)] == [b"ring"]


async def test_self_bind_is_refused(client):
    ch = await client.channel()
    await ch.exchange_declare("self_x", "fanout")
    with pytest.raises(ChannelClosedError) as exc:
        await ch.exchange_bind("self_x", "self_x", "")
    assert "406" in str(exc.value)


async def test_unbind_stops_flow(client):
    ch = await client.channel()
    await ch.exchange_declare("u_src", "fanout")
    await ch.exchange_declare("u_dst", "fanout")
    await ch.queue_declare("q_u")
    await ch.exchange_bind("u_dst", "u_src", "")
    await ch.queue_bind("q_u", "u_dst", "")
    ch.basic_publish(b"before", exchange="u_src", routing_key="")
    assert [m.body for m in await drain(ch, "q_u", 1)] == [b"before"]
    await ch.exchange_unbind("u_dst", "u_src", "")
    ch.basic_publish(b"after", exchange="u_src", routing_key="")
    await asyncio.sleep(0.05)
    assert await ch.basic_get("q_u", no_ack=True) is None


async def test_deleting_destination_removes_binding(client):
    ch = await client.channel()
    await ch.exchange_declare("d_src", "fanout")
    await ch.exchange_declare("d_dst", "fanout")
    await ch.queue_declare("q_d")
    await ch.exchange_bind("d_dst", "d_src", "")
    await ch.queue_bind("q_d", "d_dst", "")
    await ch.exchange_delete("d_dst")
    # the source's e2e binding is swept: publish routes nowhere, no crash
    ch.basic_publish(b"orphan", exchange="d_src", routing_key="")
    await asyncio.sleep(0.05)
    srv_ex = None
    # and an if_unused delete of the source now succeeds
    await ch.exchange_delete("d_src", if_unused=True)
    assert srv_ex is None


async def test_if_unused_counts_e2e_bindings(client):
    ch = await client.channel()
    await ch.exchange_declare("iu_src", "fanout")
    await ch.exchange_declare("iu_dst", "fanout")
    await ch.exchange_bind("iu_dst", "iu_src", "")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.exchange_delete("iu_src", if_unused=True)
    assert exc_info.value.reply_code == 406


async def test_default_exchange_refused(client):
    ch = await client.channel()
    await ch.exchange_declare("any_ex", "fanout")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.exchange_bind("any_ex", "", "k")
    assert exc_info.value.reply_code == 403
    ch2 = await client.channel()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch2.exchange_bind("", "any_ex", "k")
    assert exc_info.value.reply_code == 403


async def test_bind_to_missing_exchange_is_404(client):
    ch = await client.channel()
    await ch.exchange_declare("only_src", "fanout")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.exchange_bind("ghost", "only_src", "")
    assert exc_info.value.reply_code == 404


async def test_internal_exchange_reachable_only_via_e2e(client):
    ch = await client.channel()
    await ch.exchange_declare("front", "fanout")
    await ch.exchange_declare("inner", "fanout", internal=True)
    await ch.queue_declare("q_inner")
    await ch.exchange_bind("inner", "front", "")
    await ch.queue_bind("q_inner", "inner", "")
    # direct publish to the internal exchange is refused
    ch.basic_publish(b"nope", exchange="inner", routing_key="")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.queue_declare("q_inner", passive=True)  # forces the error out
    assert exc_info.value.reply_code == 403
    # but the e2e hop delivers
    ch2 = await client.channel()
    ch2.basic_publish(b"via-front", exchange="front", routing_key="")
    got = await drain(ch2, "q_inner", 1)
    assert [m.body for m in got] == [b"via-front"]


async def test_topic_source_wildcards_apply_per_hop(client):
    ch = await client.channel()
    await ch.exchange_declare("t_src", "topic")
    await ch.exchange_declare("t_dst", "topic")
    await ch.queue_declare("q_t")
    await ch.exchange_bind("t_dst", "t_src", "stock.#")
    await ch.queue_bind("q_t", "t_dst", "stock.*.nyse")
    ch.basic_publish(b"m1", exchange="t_src", routing_key="stock.ibm.nyse")
    assert [m.body for m in await drain(ch, "q_t", 1)] == [b"m1"]
    # passes the first hop but not the second
    ch.basic_publish(b"m2", exchange="t_src", routing_key="stock.ibm.nasdaq")
    await asyncio.sleep(0.05)
    assert await ch.basic_get("q_t", no_ack=True) is None


async def test_auto_delete_source_survives_queue_delete_with_live_e2e_bind(client):
    """Deleting the last bound queue must NOT auto-delete a source exchange
    that still has a live e2e binding (is_unused covers both matchers on
    the queue-delete sweep too)."""
    ch = await client.channel()
    await ch.exchange_declare("ad_src", "fanout", auto_delete=True)
    await ch.exchange_declare("ad_dst", "fanout")
    await ch.queue_declare("q_ad")
    await ch.queue_declare("q_downstream")
    await ch.queue_bind("q_ad", "ad_src", "")
    await ch.exchange_bind("ad_dst", "ad_src", "")
    await ch.queue_bind("q_downstream", "ad_dst", "")
    await ch.queue_delete("q_ad")
    # the source is still alive and still routes through the e2e hop
    ch.basic_publish(b"alive", exchange="ad_src", routing_key="")
    got = await drain(ch, "q_downstream", 1)
    assert [m.body for m in got] == [b"alive"]


async def test_durable_e2e_binding_survives_restart(tmp_path):
    db_path = str(tmp_path / "exbind.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.exchange_declare("p_src", "direct", durable=True)
    await ch.exchange_declare("p_dst", "fanout", durable=True)
    await ch.queue_declare("q_p", durable=True)
    await ch.exchange_bind("p_dst", "p_src", "k")
    await ch.queue_bind("q_p", "p_dst", "")
    await c.close()
    await srv.stop()

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ch2.basic_publish(b"revived", exchange="p_src", routing_key="k")
        got = await drain(ch2, "q_p", 1)
        assert [m.body for m in got] == [b"revived"]
        await c2.close()
    finally:
        await srv2.stop()


# -- alternate exchanges ----------------------------------------------------


async def test_alternate_exchange_catches_unroutable(client):
    ch = await client.channel()
    await ch.exchange_declare("ae_unrouted", "fanout")
    await ch.queue_declare("q_unrouted")
    await ch.queue_bind("q_unrouted", "ae_unrouted", "")
    await ch.exchange_declare("ae_main", "direct", arguments={
        "alternate-exchange": "ae_unrouted"})
    await ch.queue_declare("q_known")
    await ch.queue_bind("q_known", "ae_main", "known")

    ch.basic_publish(b"hit", exchange="ae_main", routing_key="known")
    ch.basic_publish(b"miss", exchange="ae_main", routing_key="other")
    assert [m.body for m in await drain(ch, "q_known", 1)] == [b"hit"]
    assert [m.body for m in await drain(ch, "q_unrouted", 1)] == [b"miss"]
    # the matched message did NOT also go to the alternate
    await asyncio.sleep(0.05)
    assert await ch.basic_get("q_unrouted", no_ack=True) is None


async def test_alternate_exchange_cycle_safe(client):
    ch = await client.channel()
    await ch.exchange_declare("ae_a", "direct",
                              arguments={"alternate-exchange": "ae_b"})
    await ch.exchange_declare("ae_b", "direct",
                              arguments={"alternate-exchange": "ae_a"})
    ch.basic_publish(b"nowhere", exchange="ae_a", routing_key="k")
    await asyncio.sleep(0.05)  # no hang, no crash
    ch2 = await client.channel()
    await ch2.queue_declare("ae_alive")
    ch2.basic_publish(b"ok", routing_key="ae_alive")
    assert (await drain(ch2, "ae_alive", 1))[0].body == b"ok"


async def test_alternate_exchange_suppresses_mandatory_return(client):
    """A message the alternate exchange routes counts as routed: no
    Basic.Return even with mandatory set (RabbitMQ semantics)."""
    ch = await client.channel()
    await ch.exchange_declare("ae_sink", "fanout")
    await ch.queue_declare("q_sink")
    await ch.queue_bind("q_sink", "ae_sink", "")
    await ch.exchange_declare("ae_mand", "direct", arguments={
        "alternate-exchange": "ae_sink"})
    ch.basic_publish(b"saved", exchange="ae_mand", routing_key="nope",
                     mandatory=True)
    assert [m.body for m in await drain(ch, "q_sink", 1)] == [b"saved"]
    await asyncio.sleep(0.05)
    assert ch.returns == []
    # but with no AE target bound, mandatory still returns
    await ch.queue_unbind("q_sink", "ae_sink", "")
    ch.basic_publish(b"lost", exchange="ae_mand", routing_key="nope",
                     mandatory=True)
    await asyncio.sleep(0.1)
    assert len(ch.returns) == 1 and ch.returns[0].reply_code == 312


async def test_alternate_exchange_survives_restart(tmp_path):
    db_path = str(tmp_path / "ae.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.exchange_declare("ae_p_sink", "fanout", durable=True)
        await ch.queue_declare("q_p_sink", durable=True)
        await ch.queue_bind("q_p_sink", "ae_p_sink", "")
        await ch.exchange_declare("ae_p", "direct", durable=True, arguments={
            "alternate-exchange": "ae_p_sink"})
        await c.close()
    finally:
        await srv.stop()
    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ch2.basic_publish(b"after-restart", exchange="ae_p",
                          routing_key="unbound")
        got = await drain(ch2, "q_p_sink", 1)
        assert [m.body for m in got] == [b"after-restart"]
        await c2.close()
    finally:
        await srv2.stop()


async def test_alternate_exchange_inequivalent_redeclare_rejected(client):
    """Redeclaring with a different (or newly added) alternate-exchange is
    a 406, never a silent no-op the client mistakes for an active AE."""
    ch = await client.channel()
    await ch.exchange_declare("ae_eq", "direct")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.exchange_declare("ae_eq", "direct", arguments={
            "alternate-exchange": "somewhere"})
    assert exc_info.value.reply_code == 406
    # same settings redeclare still fine
    ch2 = await client.channel()
    await ch2.exchange_declare("ae_eq", "direct")
