"""Transactional channel semantics (tx.select / tx.commit / tx.rollback).

EXCEEDS the reference, which stubs tx.* with TODO logs
(chana-mq-server .../engine/FrameStage.scala:1261-1272): here a tx channel
buffers publishes and ack/nack/reject in arrival order until commit replays
them behind the publisher-confirm durability barrier, or rollback discards
them (per 0-9-1: settled-in-tx deliveries return to unacked WITHOUT
automatic redelivery — basic.recover redelivers).
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


@pytest.fixture
async def server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def test_tx_publish_buffers_until_commit(client):
    ch = await client.channel()
    await ch.queue_declare("txq")
    await ch.tx_select()
    ch.basic_publish(b"one", routing_key="txq")
    ch.basic_publish(b"two", routing_key="txq")
    # same connection, commands processed strictly in order: this passive
    # declare observes queue state after both publishes were buffered
    ch2 = await client.channel()
    ok = await ch2.queue_declare("txq", passive=True)
    assert ok.message_count == 0
    await ch.tx_commit()
    ok = await ch2.queue_declare("txq", passive=True)
    assert ok.message_count == 2
    # committed messages deliver in publish order
    assert (await ch2.basic_get("txq", no_ack=True)).body == b"one"
    assert (await ch2.basic_get("txq", no_ack=True)).body == b"two"


async def test_tx_rollback_discards_publishes(client):
    ch = await client.channel()
    await ch.queue_declare("txq_rb")
    await ch.tx_select()
    ch.basic_publish(b"gone", routing_key="txq_rb")
    await ch.tx_rollback()
    ch2 = await client.channel()
    ok = await ch2.queue_declare("txq_rb", passive=True)
    assert ok.message_count == 0
    # the channel is immediately usable in a fresh transaction
    ch.basic_publish(b"kept", routing_key="txq_rb")
    await ch.tx_commit()
    assert (await ch2.basic_get("txq_rb", no_ack=True)).body == b"kept"


async def test_tx_ack_applies_at_commit(server, client):
    ch = await client.channel()
    await ch.queue_declare("txq_ack")
    ch.basic_publish(b"m", routing_key="txq_ack")
    msg = await ch.basic_get("txq_ack")
    assert msg is not None and msg.body == b"m"
    await ch.tx_select()
    ch.basic_ack(msg.delivery_tag)
    await ch.tx_commit()
    # settled: closing the channel must NOT requeue the message
    await ch.close()
    ch2 = await client.channel()
    assert await ch2.basic_get("txq_ack") is None


async def test_tx_rollback_returns_ack_to_unacked(client):
    ch = await client.channel()
    await ch.queue_declare("txq_rb_ack")
    ch.basic_publish(b"m", routing_key="txq_rb_ack")
    msg = await ch.basic_get("txq_rb_ack")
    await ch.tx_select()
    ch.basic_ack(msg.delivery_tag)
    await ch.tx_rollback()
    # the ack was discarded: the delivery is unacked again (not redelivered
    # automatically, per the spec note on tx.rollback) — so the plain-mode
    # semantics apply: acking it again in a new tx works
    ch.basic_ack(msg.delivery_tag)
    await ch.tx_commit()
    await ch.close()
    ch2 = await client.channel()
    assert await ch2.basic_get("txq_rb_ack") is None


async def test_tx_rollback_then_channel_close_requeues(client):
    ch = await client.channel()
    await ch.queue_declare("txq_requeue")
    ch.basic_publish(b"m", routing_key="txq_requeue")
    msg = await ch.basic_get("txq_requeue")
    await ch.tx_select()
    ch.basic_ack(msg.delivery_tag)
    await ch.tx_rollback()
    # unacked again -> channel close requeues it
    await ch.close()
    ch2 = await client.channel()
    got = await ch2.basic_get("txq_requeue", no_ack=True)
    assert got is not None and got.body == b"m" and got.redelivered


async def test_tx_open_transaction_rolls_back_on_channel_close(client):
    ch = await client.channel()
    await ch.queue_declare("txq_close")
    ch.basic_publish(b"settled", routing_key="txq_close")
    msg = await ch.basic_get("txq_close")
    await ch.tx_select()
    ch.basic_publish(b"uncommitted", routing_key="txq_close")
    ch.basic_ack(msg.delivery_tag)
    await ch.close()  # implicit rollback: publish dropped, delivery requeued
    ch2 = await client.channel()
    ok = await ch2.queue_declare("txq_close", passive=True)
    assert ok.message_count == 1
    got = await ch2.basic_get("txq_close", no_ack=True)
    assert got.body == b"settled" and got.redelivered


async def test_tx_double_settle_in_tx_raises(client):
    ch = await client.channel()
    await ch.queue_declare("txq_double")
    ch.basic_publish(b"m", routing_key="txq_double")
    msg = await ch.basic_get("txq_double")
    await ch.tx_select()
    ch.basic_ack(msg.delivery_tag)
    # second settle of the same tag inside the tx: unknown tag -> 406
    ch.basic_ack(msg.delivery_tag)
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.tx_commit()
    assert exc_info.value.reply_code == 406


async def test_tx_nack_requeue_applies_at_commit(client):
    ch = await client.channel()
    await ch.queue_declare("txq_nack")
    ch.basic_publish(b"m", routing_key="txq_nack")
    msg = await ch.basic_get("txq_nack")
    await ch.tx_select()
    ch.basic_nack(msg.delivery_tag, requeue=True)
    ch2 = await client.channel()
    ok = await ch2.queue_declare("txq_nack", passive=True)
    assert ok.message_count == 0  # not requeued yet
    await ch.tx_commit()
    got = await ch2.basic_get("txq_nack", no_ack=True)
    assert got is not None and got.body == b"m" and got.redelivered


async def test_tx_reject_drop_applies_at_commit(client):
    ch = await client.channel()
    await ch.queue_declare("txq_rej")
    ch.basic_publish(b"m", routing_key="txq_rej")
    msg = await ch.basic_get("txq_rej")
    await ch.tx_select()
    ch.basic_reject(msg.delivery_tag, requeue=False)
    await ch.tx_commit()
    await ch.close()
    ch2 = await client.channel()
    assert await ch2.basic_get("txq_rej") is None


async def test_tx_and_confirm_mutually_exclusive(client):
    ch = await client.channel()
    await ch.confirm_select()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.tx_select()
    assert exc_info.value.reply_code == 406

    ch2 = await client.channel()
    await ch2.tx_select()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch2.confirm_select()
    assert exc_info.value.reply_code == 406


async def test_tx_commit_without_select_raises(client):
    ch = await client.channel()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.tx_commit()
    assert exc_info.value.reply_code == 406
    ch2 = await client.channel()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch2.tx_rollback()
    assert exc_info.value.reply_code == 406


async def test_tx_empty_commit_and_rollback_ok(client):
    ch = await client.channel()
    await ch.tx_select()
    await ch.tx_commit()
    await ch.tx_rollback()
    await ch.tx_commit()


async def test_tx_mandatory_return_renders_at_commit(client):
    ch = await client.channel()
    await ch.tx_select()
    ch.basic_publish(b"nowhere", routing_key="no.such.queue", mandatory=True)
    # buffered: no Return yet (observe via an ordered round trip)
    await ch.tx_rollback()
    await asyncio.sleep(0.05)
    assert ch.returns == []
    ch.basic_publish(b"nowhere", routing_key="no.such.queue", mandatory=True)
    await ch.tx_commit()
    await asyncio.sleep(0.05)
    assert len(ch.returns) == 1
    assert ch.returns[0].reply_code == 312  # NO_ROUTE


async def test_tx_interleaved_publish_and_ack_order(client):
    """Ops replay in arrival order: publish, ack, publish inside one tx."""
    ch = await client.channel()
    await ch.queue_declare("txq_order")
    ch.basic_publish(b"first", routing_key="txq_order")
    msg = await ch.basic_get("txq_order")
    await ch.tx_select()
    ch.basic_publish(b"second", routing_key="txq_order")
    ch.basic_ack(msg.delivery_tag)
    ch.basic_publish(b"third", routing_key="txq_order")
    await ch.tx_commit()
    ch2 = await client.channel()
    assert (await ch2.basic_get("txq_order", no_ack=True)).body == b"second"
    assert (await ch2.basic_get("txq_order", no_ack=True)).body == b"third"
    assert await ch2.basic_get("txq_order") is None


async def test_tx_persistent_commit_survives_restart(tmp_path):
    """Tx.CommitOk is a durability barrier: a committed persistent publish
    to a durable queue survives a broker restart; an uncommitted one
    (connection died mid-tx) does not."""
    db_path = str(tmp_path / "tx.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("tx_durable", durable=True)
    await ch.tx_select()
    ch.basic_publish(b"committed", routing_key="tx_durable",
                     properties=PERSISTENT)
    await ch.tx_commit()
    ch.basic_publish(b"uncommitted", routing_key="tx_durable",
                     properties=PERSISTENT)
    # drive the publish onto the server before dropping the connection
    ch2 = await c.channel()
    await ch2.queue_declare("tx_durable", passive=True)
    await c.close()
    await srv.stop()

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch3 = await c2.channel()
        ok = await ch3.queue_declare("tx_durable", durable=True, passive=True)
        assert ok.message_count == 1
        got = await ch3.basic_get("tx_durable", no_ack=True)
        assert got.body == b"committed"
        await c2.close()
    finally:
        await srv2.stop()


async def test_tx_partial_commit_failure_restores_parked_settles(client):
    """A replayed publish that fails mid-commit (deleted exchange) closes
    the channel — but parked settles ordered after it must NOT vanish: the
    deliveries return to unacked and the channel teardown requeues them."""
    ch = await client.channel()
    await ch.exchange_declare("tx_doomed_ex", "direct")
    await ch.queue_declare("txq_partial")
    ch.basic_publish(b"held", routing_key="txq_partial")
    msg = await ch.basic_get("txq_partial")
    await ch.tx_select()
    # buffered publish to an exchange that will be gone at commit time,
    # ordered BEFORE the ack
    ch.basic_publish(b"x", exchange="tx_doomed_ex", routing_key="k")
    ch.basic_ack(msg.delivery_tag)
    ch2 = await client.channel()
    await ch2.exchange_delete("tx_doomed_ex")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.tx_commit()
    assert exc_info.value.reply_code == 404
    # the ack never applied and the delivery was requeued by the close
    await asyncio.sleep(0.05)
    got = await ch2.basic_get("txq_partial", no_ack=True)
    assert got is not None and got.body == b"held" and got.redelivered


async def test_tx_parked_settles_hold_global_prefetch_budget(client):
    """Stashing an ack inside a tx must not reopen the channel-global
    prefetch window before the commit applies it."""
    ch = await client.channel()
    await ch.queue_declare("txq_qos")
    await ch.basic_qos(prefetch_count=1, global_=True)
    ch.basic_publish(b"one", routing_key="txq_qos")
    ch.basic_publish(b"two", routing_key="txq_qos")
    cb_msgs = []
    await ch.basic_consume("txq_qos", cb_msgs.append)
    await asyncio.sleep(0.1)
    assert [m.body for m in cb_msgs] == [b"one"]  # window of 1
    await ch.tx_select()
    ch.basic_ack(cb_msgs[0].delivery_tag)
    ch2 = await client.channel()
    await ch2.queue_declare("txq_qos", passive=True)  # ordering barrier
    await asyncio.sleep(0.1)
    # the parked ack must NOT have opened the window
    assert [m.body for m in cb_msgs] == [b"one"]
    await ch.tx_commit()
    await asyncio.sleep(0.1)
    assert [m.body for m in cb_msgs] == [b"one", b"two"]


async def test_tx_buffered_publishes_count_against_memory_gauge(server, client):
    """A flood parked inside a never-committed tx is visible to the broker
    memory gauge (and thus the backpressure gate)."""
    broker = server.broker
    ch = await client.channel()
    await ch.queue_declare("txq_mem")
    await ch.tx_select()
    body = b"x" * 4096
    before = broker.resident_bytes
    for _ in range(8):
        ch.basic_publish(body, routing_key="txq_mem")
    ch2 = await client.channel()
    await ch2.queue_declare("txq_mem", passive=True)  # ordering barrier
    assert broker.resident_bytes >= before + 8 * len(body)
    await ch.tx_rollback()
    await ch2.queue_declare("txq_mem", passive=True)
    assert broker.resident_bytes == before


async def test_tx_commit_store_failure_never_sends_commit_ok(tmp_path):
    """Tx.CommitOk is a durability barrier: a store failure covering the
    commit's persistent writes must error the channel/connection instead of
    acknowledging — and the message must not silently survive as a ghost."""
    db_path = str(tmp_path / "txfail.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    store = srv.broker.store
    orig_insert = store.insert_message_nowait

    def failing_insert(msg):
        if msg.routing_key == "tx_fail_q":
            store._submit_nowait(
                lambda db: db.execute("INSERT INTO no_such_table VALUES (1)"))
            return
        orig_insert(msg)

    store.insert_message_nowait = failing_insert
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("tx_fail_q", durable=True)
    await ch.tx_select()
    ch.basic_publish(b"doomed", routing_key="tx_fail_q",
                     properties=PERSISTENT)
    with pytest.raises(Exception):
        await ch.tx_commit()
    store.insert_message_nowait = orig_insert
    await c.close()
    await srv.stop()

    # after a restart, the failed commit left no durable ghost ready to
    # deliver a message the client was told (nothing) about
    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        got = await ch2.basic_get("tx_fail_q", no_ack=True)
        assert got is None
        await c2.close()
    finally:
        await srv2.stop()
