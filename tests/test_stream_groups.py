"""Shared / key-shared consumer groups on stream queues (streams/groups.py).

Covers the x-group consume contract: one shared committed cursor per
group, record spread across members (round-robin for shared, consistent-
hash + sticky keys for key-shared), per-key ordering through member
disconnects, resume-from-committed across full member churn, and the
consume-time argument validation."""

import asyncio

import pytest

from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError
from chanamq_tpu.streams.groups import GROUP_CURSOR_PREFIX

pytestmark = pytest.mark.asyncio

STREAM = {"x-queue-type": "stream"}


async def start_server():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    return srv


def _grp_args(name, mode=None, offset="first"):
    args = {"x-group": name, "x-stream-offset": offset}
    if mode is not None:
        args["x-group-type"] = mode
    return args


async def test_shared_group_partitions_stream():
    """Two members of one shared group split the log: every record is
    delivered exactly once across the group, and the group cursor commits
    to the tail once everything is acked."""
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sg1", durable=True, arguments=STREAM)
        await ch.basic_qos(prefetch_count=4)

        got_a, got_b = [], []
        done = asyncio.get_event_loop().create_future()

        def on_msg(bucket):
            def cb(msg):
                bucket.append(int(msg.body))
                ch.basic_ack(msg.delivery_tag)
                if (len(got_a) + len(got_b)) >= 40 and not done.done():
                    done.set_result(None)
            return cb

        await ch.basic_consume("sg1", on_msg(got_a), consumer_tag="m-a",
                               arguments=_grp_args("g"))
        await ch.basic_consume("sg1", on_msg(got_b), consumer_tag="m-b",
                               arguments=_grp_args("g"))
        for i in range(40):
            ch.basic_publish(str(i).encode(), routing_key="sg1")
        await asyncio.wait_for(done, 5)
        await asyncio.sleep(0.05)  # let the trailing acks land
        assert sorted(got_a + got_b) == list(range(40))
        assert got_a and got_b  # round-robin used both members
        sq = srv.broker.vhosts["/"].queues["sg1"]
        # committed floor reaches the last record (offsets are 1-based)
        assert sq.committed[GROUP_CURSOR_PREFIX + "g"] == sq.next_offset - 1
        assert srv.broker.metrics.stream_groups_created == 1
        assert srv.broker.metrics.stream_group_deliveries == 40
        await c.close()
    finally:
        await srv.stop()


async def test_group_resumes_from_committed_after_full_churn():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sg2", durable=True, arguments=STREAM)
        for i in range(10):
            ch.basic_publish(str(i).encode(), routing_key="sg2")
        await asyncio.sleep(0.05)

        async def drain(n):
            got = []
            done = asyncio.get_event_loop().create_future()

            def cb(msg):
                got.append(int(msg.body))
                ch.basic_ack(msg.delivery_tag)
                if len(got) >= n and not done.done():
                    done.set_result(None)

            tag = await ch.basic_consume("sg2", cb,
                                         arguments=_grp_args("g2"))
            await asyncio.wait_for(done, 5)
            await asyncio.sleep(0.05)
            await ch.basic_cancel(tag)
            return got

        assert await drain(10) == list(range(10))
        # group now memberless; its committed offset survives
        for i in range(10, 15):
            ch.basic_publish(str(i).encode(), routing_key="sg2")
        await asyncio.sleep(0.05)
        # the rejoining member asks for "first" but the committed group
        # cursor wins: only the unconsumed suffix arrives
        assert await drain(5) == list(range(10, 15))
        await c.close()
    finally:
        await srv.stop()


async def test_key_shared_keys_stick_to_one_member():
    """Without churn, each routing key lands on exactly one member, and
    each member sees its keys' sequences in publish order."""
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sg3", durable=True, arguments=STREAM)
        # fanout exchange so the partition key (routing key) can vary per
        # record while everything still lands in the stream
        await ch.exchange_declare("sg3x", "fanout")
        await ch.queue_bind("sg3", "sg3x", "")
        keys = [f"k{i}" for i in range(8)]
        total = 20 * len(keys)

        seen = {}  # member -> [(key, seq)]
        done = asyncio.get_event_loop().create_future()

        def on_msg(member):
            def cb(msg):
                seen.setdefault(member, []).append(
                    (msg.routing_key, int(msg.body)))
                ch.basic_ack(msg.delivery_tag)
                if sum(len(v) for v in seen.values()) >= total \
                        and not done.done():
                    done.set_result(None)
            return cb

        for member in ("a", "b", "c"):
            await ch.basic_consume(
                "sg3", on_msg(member), consumer_tag=f"m-{member}",
                arguments=_grp_args("g3", "key-shared"))
        for seq in range(20):
            for key in keys:
                ch.basic_publish(str(seq).encode(), exchange="sg3x",
                                 routing_key=key)
        await asyncio.wait_for(done, 5)
        owners = {}
        for member, msgs in seen.items():
            per_key = {}
            for key, seq in msgs:
                owners.setdefault(key, set()).add(member)
                per_key.setdefault(key, []).append(seq)
            for key, seqs in per_key.items():
                assert seqs == sorted(seqs), (member, key, seqs)
        assert all(len(m) == 1 for m in owners.values()), owners
        assert len(seen) > 1  # the ring actually spread the keyspace
        await c.close()
    finally:
        await srv.stop()


async def test_key_shared_disconnect_redelivers_in_key_order():
    """A member dropping mid-flight with unacked deliveries: its records
    redeliver to the survivor BEFORE any later record of the same keys
    (head-of-line + redelivery heap), so per-key ack order stays strictly
    increasing — the chaos-soak invariant, asserted deterministically."""
    srv = await start_server()
    try:
        pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        pch = await pub.channel()
        await pch.queue_declare("sg4", durable=True, arguments=STREAM)
        await pch.exchange_declare("sg4x", "fanout")
        await pch.queue_bind("sg4", "sg4x", "")
        keys = [f"k{i}" for i in range(4)]
        total = 10 * len(keys)

        # victim: takes deliveries but never acks, then the connection dies
        victim = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        vch = await victim.channel()
        await vch.basic_qos(prefetch_count=6)
        victim_got = []
        vch_ready = asyncio.get_event_loop().create_future()

        def victim_cb(msg):
            victim_got.append(msg.routing_key)
            if len(victim_got) >= 6 and not vch_ready.done():
                vch_ready.set_result(None)

        await vch.basic_consume("sg4", victim_cb, consumer_tag="victim",
                                arguments=_grp_args("g4", "key-shared"))
        for seq in range(10):
            for key in keys:
                pch.basic_publish(str(seq).encode(), exchange="sg4x",
                                  routing_key=key)
        await asyncio.wait_for(vch_ready, 5)
        assert victim_got  # it really held deliveries hostage

        survivor = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        sch = await survivor.channel()
        acked = []  # (key, seq) in ack order
        done = asyncio.get_event_loop().create_future()

        def survivor_cb(msg):
            acked.append((msg.routing_key, int(msg.body)))
            sch.basic_ack(msg.delivery_tag)
            if len(acked) >= total and not done.done():
                done.set_result(None)

        await sch.basic_consume("sg4", survivor_cb, consumer_tag="survivor",
                                arguments=_grp_args("g4", "key-shared"))
        # every key is stuck to the victim, so the survivor gets nothing
        # until the disconnect unsticks them via requeue
        await asyncio.sleep(0.1)
        assert not acked
        await victim.close()  # release_all requeues its in-flight

        await asyncio.wait_for(done, 5)
        await asyncio.sleep(0.05)
        per_key = {}
        for key, seq in acked:
            per_key.setdefault(key, []).append(seq)
        for key, seqs in per_key.items():
            # strictly increasing: redelivered records arrived (and were
            # acked) before any later record of the same key
            assert seqs == sorted(seqs) == sorted(set(seqs)), (key, seqs)
        assert sorted(n for s in per_key.values() for n in s) \
            == sorted(list(range(10)) * len(keys))
        sq = srv.broker.vhosts["/"].queues["sg4"]
        assert sq.committed[GROUP_CURSOR_PREFIX + "g4"] == sq.next_offset - 1
        await survivor.close()
        await pub.close()
    finally:
        await srv.stop()


async def test_group_argument_validation():
    srv = await start_server()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("sg5", durable=True, arguments=STREAM)
        await ch.queue_declare("classic-q")
        await ch.basic_consume("sg5", lambda m: None, consumer_tag="ok",
                               arguments=_grp_args("g5", "shared"))
        # mode conflict with the existing group
        with pytest.raises(ChannelClosedError):
            ch2 = await c.channel()
            await ch2.basic_consume(
                "sg5", lambda m: None,
                arguments=_grp_args("g5", "key-shared"))
        # unknown mode
        with pytest.raises(ChannelClosedError):
            ch3 = await c.channel()
            await ch3.basic_consume(
                "sg5", lambda m: None, arguments=_grp_args("x", "bogus"))
        # x-group on a classic queue
        with pytest.raises(ChannelClosedError):
            ch4 = await c.channel()
            await ch4.basic_consume(
                "classic-q", lambda m: None, arguments={"x-group": "g"})
        # x-group-type without x-group
        with pytest.raises(ChannelClosedError):
            ch5 = await c.channel()
            await ch5.basic_consume(
                "sg5", lambda m: None,
                arguments={"x-group-type": "shared"})
        await c.close()
    finally:
        await srv.stop()
