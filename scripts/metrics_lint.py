#!/usr/bin/env python3
"""Metrics-registry lint: every Prometheus series the broker can export
must be documented in README.md.

The exported universe is assembled from the three places a series can be
born (rest/admin.py `_prometheus`):

1. every `Metrics.snapshot()` key — each becomes `chanamq_<key>`;
2. every `Metrics.histograms()` family — `chanamq_<name>` plus the
   derived `_bucket`/`_sum`/`_count` series (the family name documents
   all of them);
3. every literal `chanamq_[a-z0-9_]+` string in `chanamq_tpu/**/*.py`
   (labeled families emitted outside the snapshot loop, e.g.
   `chanamq_queue_messages`, `chanamq_slo_burn_rate`).

A name counts as documented when README.md contains it verbatim, via a
brace group (`chanamq_slo_{budget_remaining,burn_rate}`), or via a
prefix wildcard (`chanamq_stream_*`). Run with no arguments from
anywhere inside the repo; exits 1 listing every undocumented series so
tier1.sh can gate on it.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

NAME_RE = re.compile(r"chanamq_[a-z0-9_]+")
# `chanamq_foo_{a,b}` in prose documents chanamq_foo_a and chanamq_foo_b;
# label sets like `chanamq_alert_firing{rule,scope}` contain no brace
# directly after an underscore, so the base-name regex handles them
BRACE_RE = re.compile(r"(chanamq_(?:[a-z0-9_]+_)?)\{([a-z0-9_,]+)\}")


def exported_names() -> set[str]:
    from chanamq_tpu.utils.metrics import Metrics

    metrics = Metrics()
    names = {f"chanamq_{key}" for key in metrics.snapshot()}
    names |= {f"chanamq_{name}" for name in metrics.histograms()}
    for path in sorted((ROOT / "chanamq_tpu").rglob("*.py")):
        # a trailing underscore is a docstring wildcard/brace-group stub
        # (`chanamq_forecast_*`, `chanamq_slo_{...}`), not a series — the
        # real names are literal at their emission sites
        names |= {n for n in NAME_RE.findall(path.read_text())
                  if not n.endswith("_")}
    # histogram families document their derived series as one name
    for name in {f"chanamq_{n}" for n in metrics.histograms()}:
        for suffix in ("_bucket", "_sum", "_count"):
            names.discard(name + suffix)
    return names


def documented(readme: str) -> "tuple[set[str], set[str]]":
    """(exact names, prefixes) the README vouches for."""
    # trailing-underscore matches are brace-group stubs, not names
    exact = {n for n in NAME_RE.findall(readme) if not n.endswith("_")}
    for base, group in BRACE_RE.findall(readme):
        exact |= {base + part for part in group.split(",") if part}
    prefixes = {
        m.group(1) for m in re.finditer(r"(chanamq_[a-z0-9_]+_)\*", readme)}
    return exact, prefixes


def exemplar_gaps() -> "tuple[list[str], list[str]]":
    """(uncovered, contradictions): histogram families with neither
    exemplar support nor an explicit exemption, and families listed as
    BOTH supported and exempt. Exemplar support is declared on
    AdminServer (`_EXEMPLAR_FAMILIES` by name, `_EXEMPLAR_PREFIXES` by
    prefix); a family an operator can scrape but never join to a trace
    must be a deliberate decision recorded in `_EXEMPLAR_EXEMPT`."""
    from chanamq_tpu.rest.admin import AdminServer
    from chanamq_tpu.trace.runtime import TraceRuntime
    from chanamq_tpu.utils.metrics import Metrics

    metrics = Metrics()
    # installing a runtime registers the per-stage trace_*_us families,
    # exactly as a tracing-enabled boot does
    TraceRuntime(metrics=metrics)
    covered = set(AdminServer._EXEMPLAR_FAMILIES)
    exempt = set(AdminServer._EXEMPLAR_EXEMPT)
    prefixes = tuple(AdminServer._EXEMPLAR_PREFIXES)
    uncovered, contradictions = [], []
    for name in sorted(metrics.histograms()):
        has_support = name in covered or name.startswith(prefixes)
        if has_support and name in exempt:
            contradictions.append(name)
        elif not has_support and name not in exempt:
            uncovered.append(name)
    return uncovered, contradictions


def main() -> int:
    readme = (ROOT / "README.md").read_text()
    exact, prefixes = documented(readme)
    missing = sorted(
        name for name in exported_names()
        if name not in exact
        and not any(name.startswith(p) for p in prefixes))
    if missing:
        print("metrics lint: undocumented Prometheus series "
              f"({len(missing)}) — add them to a README metric table:")
        for name in missing:
            print(f"  {name}")
        return 1
    uncovered, contradictions = exemplar_gaps()
    if uncovered or contradictions:
        for name in uncovered:
            print(f"metrics lint: histogram {name!r} has no exemplar "
                  "support — add it to AdminServer._EXEMPLAR_FAMILIES "
                  "(or _EXEMPLAR_EXEMPT with a reason)")
        for name in contradictions:
            print(f"metrics lint: histogram {name!r} is both exemplar-"
                  "supported and exempt — pick one")
        return 1
    print("metrics lint: every exported chanamq_* series is documented; "
          "every histogram family has exemplar support or an exemption")
    return 0


if __name__ == "__main__":
    sys.exit(main())
