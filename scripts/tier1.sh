#!/usr/bin/env bash
# Tier-1 gate: the exact ROADMAP.md verify line, then short bench smokes —
# a 2-node cluster run so the binary interconnect (push_many / settle_many
# / deliver_many over the data plane) gets exercised end to end, and a
# stream run for the segmented-log dispatch path (bench.py --stream:
# 1 producer, 3 cursors at first/next/timestamp).
set -u
cd "$(dirname "$0")/.."

# Native pipeline gate: rebuild the library from a clean tree so the suite
# below exercises the freshly-built scanner/encoder (a stale .so silently
# falling back to Python would pass every parity test while benching the
# wrong thing). Parity fuzz runs under BOTH backends: native on, and
# CHANAMQ_NATIVE=0 for the pure-Python twin the fallback path depends on.
if command -v g++ >/dev/null 2>&1 || command -v c++ >/dev/null 2>&1; then
    echo "tier1: native rebuild from clean"
    make -C native clean && make -C native || {
        rc=$?
        echo "tier1: native build FAILED (rc=$rc)" >&2
        exit "$rc"
    }
    python - <<'EOF' || { echo "tier1: native pipeline unavailable after clean build" >&2; exit 1; }
from chanamq_tpu import native_ext
assert native_ext.available(), "native library failed to load"
assert native_ext.pipeline_available(), "pipeline entry points missing"
EOF
    echo "tier1: native parity fuzz (both backends)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest \
            tests/test_native_pipeline.py tests/test_native.py -q \
            -p no:cacheprovider -p no:randomly || {
        rc=$?
        echo "tier1: native parity fuzz FAILED (rc=$rc)" >&2
        exit "$rc"
    }
    timeout -k 10 300 env JAX_PLATFORMS=cpu CHANAMQ_NATIVE=0 python -m pytest \
            tests/test_frame.py tests/test_golden_wire.py -q \
            -p no:cacheprovider -p no:randomly || {
        rc=$?
        echo "tier1: pure-Python twin (CHANAMQ_NATIVE=0) FAILED (rc=$rc)" >&2
        exit "$rc"
    }
else
    echo "tier1: no C++ compiler — skipping native rebuild gate"
fi

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "$rc" -ne 0 ]; then
    echo "tier1: pytest FAILED (rc=$rc)" >&2
    exit "$rc"
fi

echo "tier1: metrics-registry lint (every exported chanamq_* series documented)"
python scripts/metrics_lint.py || {
    rc=$?
    echo "tier1: metrics lint FAILED (rc=$rc) — undocumented Prometheus series" >&2
    exit "$rc"
}

echo "tier1: 2-node cluster bench smoke (5 s)"
BENCH_SECONDS=5 timeout -k 10 120 python bench.py --cluster || {
    rc=$?
    echo "tier1: cluster bench smoke FAILED (rc=$rc)" >&2
    exit "$rc"
}

echo "tier1: traced 2-node cluster smoke (sample-rate 1.0, stitched-trace gate)"
BENCH_TRACE=1 BENCH_SECONDS=5 timeout -k 10 120 python bench.py --cluster || {
    rc=$?
    echo "tier1: traced cluster smoke FAILED (rc=$rc) — no stitched cross-node trace?" >&2
    exit "$rc"
}

echo "tier1: seeded chaos soak smoke (~5 s: partition + owner crash + slow store)"
# health-gated: the soak itself fails (violation -> exit 1) unless both
# nodes report ready before load AND the scripted alert phase fires
# exactly backlog-growth + consumer-stall; the grep double-checks the
# firing set landed in the report rather than the phase being skipped
CHAOS_MESSAGES=80 timeout -k 10 180 python bench.py --chaos --seed 42 \
        | tee /tmp/_t1_chaos.json || {
    rc=$?
    echo "tier1: chaos soak smoke FAILED (rc=$rc) — invariant violation or harness error" >&2
    exit "$rc"
}
grep -q '"fired_rules": \["backlog-growth", "consumer-stall"\]' /tmp/_t1_chaos.json || {
    echo "tier1: chaos soak report missing the exact alert firings" >&2
    exit 1
}
grep -q '"bus_stream_exact": true' /tmp/_t1_chaos.json || {
    echo "tier1: chaos soak event-bus stream did not match the engine history" >&2
    exit 1
}

echo "tier1: overload soak smoke (~7 s: memory-pressure chaos, refuse + recover)"
# the soak itself fails (violation -> exit 1) on confirmed loss, missing
# refusals/paging, or a broken channel.flow resume; the grep double-checks
# the broker stayed under the accounted-byte ceiling in the report
timeout -k 10 180 python bench.py --overload --seed 7 \
        | tee /tmp/_t1_overload.json || {
    rc=$?
    echo "tier1: overload soak smoke FAILED (rc=$rc) — flow-ladder invariant violation" >&2
    exit "$rc"
}
grep -q '"under_hard_limit": true' /tmp/_t1_overload.json || {
    echo "tier1: overload soak exceeded the accounted-byte hard limit" >&2
    exit 1
}
# the ISSUE-15 live-demo path: a consumer on amq.chanamq.event must see
# the stage escalation, the memory-pressure alert and an slo.burn-rate
# event, and the SLO budget must actually draw down
grep -q '"event_stream_ok": true' /tmp/_t1_overload.json || {
    echo "tier1: overload soak event-bus consumer missed a required event" >&2
    exit 1
}
grep -q '"slo_burned": true' /tmp/_t1_overload.json || {
    echo "tier1: overload soak SLO budget never drew down" >&2
    exit 1
}

echo "tier1: elasticity soak smoke (~30 s: join, drain, kill -9, fenced stale owner, x2 runs)"
# the soak itself fails (violation -> exit 1) on confirmed loss, dual
# holders at quiesce, an unfenced stale-epoch ship, a non-contiguous
# stream resume, or same-seed runs whose normalized decision/evacuation
# logs differ; the grep double-checks at least one stale ship was refused
timeout -k 10 300 python bench.py --elastic --seed 11 \
        | tee /tmp/_t1_elastic.json || {
    rc=$?
    echo "tier1: elasticity soak smoke FAILED (rc=$rc) — lifecycle invariant violation" >&2
    exit "$rc"
}
grep -q '"stale_epoch_refused": [1-9]' /tmp/_t1_elastic.json || {
    echo "tier1: elasticity soak never refused a stale-epoch ship" >&2
    exit 1
}

echo "tier1: control soak smoke (~10 s: pre-armed vs reactive spike, x4 runs)"
# the soak itself fails (violation -> exit 1) unless the pre-armed run
# beats the reactive ladder (strictly lower max stage, strictly fewer
# refusals), the same-seed decision logs compare byte-identical, the
# dry run provably mutates nothing and no run loses a confirmed
# message; the grep double-checks the stage delta landed in the report
timeout -k 10 240 python bench.py --control --seed 7 \
        | tee /tmp/_t1_control.json || {
    rc=$?
    echo "tier1: control soak smoke FAILED (rc=$rc) — predictive-control invariant violation" >&2
    exit "$rc"
}
grep -q '"violations": \[\]' /tmp/_t1_control.json || {
    echo "tier1: control soak report carries violations" >&2
    exit 1
}

echo "tier1: control overhead smoke (5 s x2: control plane <= 2%)"
# same retry rationale as the telemetry overhead gate below: the off/on
# delta from two independent runs is noise-prone on shared boxes
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --control-overhead; then
        ok=1
        break
    fi
    echo "tier1: control overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: control overhead smoke FAILED (3 attempts) — control plane cost over budget" >&2
    exit 1
}

echo "tier1: connection-churn smoke (500 cycles: no accounted-bytes leak)"
timeout -k 10 180 python bench.py --churn || {
    rc=$?
    echo "tier1: connection-churn smoke FAILED (rc=$rc) — accounted-bytes leak" >&2
    exit "$rc"
}

echo "tier1: telemetry overhead smoke (5 s x2: per-entity sampling <= 2%)"
# the off/on delta is measured from two independent 5 s runs, so on a
# shared/virtualized box a CPU-steal burst in either run can swamp the
# 2% budget with pure noise (observed swings of +/-10% run to run while
# the sampled tick cost itself is ~50us, 0.05% of a core). Retry up to
# 3 attempts: a real systematic overhead fails every attempt
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --telemetry-overhead; then
        ok=1
        break
    fi
    echo "tier1: telemetry overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: telemetry overhead smoke FAILED (3 attempts) — sampling cost over budget" >&2
    exit 1
}

echo "tier1: profile attribution smoke (5 s: >=5 stages, >=90% CPU attributed, stacks)"
# ledger + stack sampler on, /admin/profile scraped around the load
# window. Retried: the 90% attribution floor is tight when a CPU-steal
# burst lands inside the measurement window on a shared box
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --profile; then
        ok=1
        break
    fi
    echo "tier1: profile smoke attempt $attempt failed, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: profile smoke FAILED (3 attempts) — attribution or stacks gate" >&2
    exit 1
}

echo "tier1: profile overhead smoke (5 s x2: cost ledger <= 2%)"
# same retry rationale as the other overhead gates: two independent 5 s
# runs carry +/-10% noise; the ledger's true cost is batch-granular
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --profile-overhead; then
        ok=1
        break
    fi
    echo "tier1: profile overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: profile overhead smoke FAILED (3 attempts) — ledger cost over budget" >&2
    exit 1
}

echo "tier1: event-bus overhead smoke (5 s x2: bus + firehose, nothing bound, <= 2%)"
# same retry rationale as the other overhead gates
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --event-overhead; then
        ok=1
        break
    fi
    echo "tier1: event overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: event overhead smoke FAILED (3 attempts) — bus/firehose cost over budget" >&2
    exit 1
}

echo "tier1: otel overhead smoke (5 s x2: OTLP export vs tracing alone <= 2%)"
# both variants run tracing at the default 1% sample rate; the delta
# isolates the otel layer (header probe + finish-hook enqueue + flusher
# against a dead collector). Same retry rationale as the other gates
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --otel-overhead; then
        ok=1
        break
    fi
    echo "tier1: otel overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: otel overhead smoke FAILED (3 attempts) — OTLP export cost over budget" >&2
    exit 1
}

echo "tier1: SLO overhead smoke (5 s x2: SLI sampler + burn-rate eval <= 2%)"
# same retry rationale as the other overhead gates
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --slo-overhead; then
        ok=1
        break
    fi
    echo "tier1: SLO overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: SLO overhead smoke FAILED (3 attempts) — SLO engine cost over budget" >&2
    exit 1
}

echo "tier1: bench-trajectory regression gate (5 s x2, record + gate)"
# first leg seeds/extends BENCH_trajectory.jsonl (and judges against the
# previous recorded baseline when one exists); second leg re-judges
# against the freshly recorded line — two consecutive --regress runs
# against the same baseline must agree. Both retried for box noise; a
# real regression moves wall AND CPU together and fails every attempt
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 240 python bench.py --regress --record; then
        ok=1
        break
    fi
    echo "tier1: regress record attempt $attempt failed, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: bench regression gate FAILED (3 attempts) — wall+CPU cost regressed" >&2
    exit 1
}
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 240 python bench.py --regress; then
        ok=1
        break
    fi
    echo "tier1: regress gate attempt $attempt failed, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: bench regression re-gate FAILED (3 attempts)" >&2
    exit 1
}

echo "tier1: 2-shard node smoke (5 s x2: multi-process + UDS interconnect)"
# a real multi-process node: supervisor + 2 SO_REUSEPORT workers, queue
# ownership split by the hash ring, cross-shard messages over the Unix
# data plane. Gates on harness health (all shards converge, per-shard
# admin scrape works, no child errors); throughput/speedup are reported,
# not asserted — this box may be single-core
BENCH_SECONDS=5 timeout -k 10 240 python bench.py --shard 2 || {
    rc=$?
    echo "tier1: 2-shard smoke FAILED (rc=$rc)" >&2
    exit "$rc"
}

echo "tier1: WAL kill-9 recovery smoke (confirmed set must survive SIGKILL)"
# pumps publisher confirms against a WAL-backed broker, SIGKILLs it
# mid-stream, restarts on the same data dir and asserts every confirmed
# message is redelivered — a confirm means the group commit fsynced it
timeout -k 10 120 python bench.py --wal-recovery || {
    rc=$?
    echo "tier1: WAL recovery smoke FAILED (rc=$rc) — confirmed messages lost after kill -9" >&2
    exit "$rc"
}

echo "tier1: stream bench smoke (5 s)"
BENCH_SECONDS=5 timeout -k 10 120 python bench.py --stream || {
    rc=$?
    echo "tier1: stream bench smoke FAILED (rc=$rc)" >&2
    exit "$rc"
}

echo "tier1: rpc bench smoke (request-reply, exclusive reply queues)"
BENCH_SECONDS=5 timeout -k 10 120 python bench.py --rpc || {
    rc=$?
    echo "tier1: rpc bench smoke FAILED (rc=$rc)" >&2
    exit "$rc"
}

echo "tier1: dlx/priority scenario smoke (burst drain order + exactly-once DLX)"
# the bench itself fails (exit 1) on any priority inversion, lost or
# duplicated dead-letter, or malformed x-death header
BENCH_SECONDS=5 timeout -k 10 240 python bench.py --dlx || {
    rc=$?
    echo "tier1: dlx/priority smoke FAILED (rc=$rc) — ordering or dead-letter violation" >&2
    exit "$rc"
}

echo "tier1: semantics soak smoke (~8 s: Tx kill at the WAL boundary + TTL DLX under faults)"
# the soak itself fails (violation -> exit 1) on confirmed loss, a
# partially recovered transaction, post-rollback ghosts, or non-exactly-
# once dead-lettering; the grep double-checks both same-seed repeats
# serialized byte-identically
timeout -k 10 300 python bench.py --semantics-soak --seed 42 \
        | tee /tmp/_t1_semantics.json || {
    rc=$?
    echo "tier1: semantics soak smoke FAILED (rc=$rc) — delivery-semantics invariant violation" >&2
    exit "$rc"
}
grep -q '"deterministic": true' /tmp/_t1_semantics.json || {
    echo "tier1: semantics soak repeats were not byte-identical" >&2
    exit 1
}

echo "tier1: semantics overhead smoke (5 s x2: disabled-path cost <= 2%)"
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --semantics-overhead; then
        ok=1
        break
    fi
    echo "tier1: semantics overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: semantics overhead smoke FAILED (3 attempts) — semantics disabled-path cost over budget" >&2
    exit 1
}

echo "tier1: federation soak smoke (~15 s x2: sever mid-stream, failover, heal)"
# two independent clusters joined by one link; the soak itself fails
# (violation -> exit 1) on confirmed loss, a non-contiguous cursor
# resume on the mirror, duplicate post-settle deliveries or a mirror
# audit read that differs from the published set; the greps double-check
# both same-seed repeats serialized byte-identically and violation-free
# retried like the overhead gates: the soak's quiesce/failover waits are
# deadline-based, so a CPU-steal burst on a shared box can time one out;
# a real invariant violation fails every attempt
ok=""
for attempt in 1 2 3; do
    if timeout -k 10 300 python bench.py --federation --seed 42 \
            | tee /tmp/_t1_federation.json \
            && grep -q '"deterministic": true' /tmp/_t1_federation.json \
            && grep -q '"violations": \[\]' /tmp/_t1_federation.json; then
        ok=1
        break
    fi
    echo "tier1: federation soak attempt $attempt failed, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: federation soak smoke FAILED (3 attempts) — cross-cluster invariant violation" >&2
    exit 1
}

echo "tier1: federation overhead smoke (5 s x2: idle-link cost <= 2%)"
# same retry rationale as the other overhead gates: federation is enabled
# with zero links configured, so the per-publish cost is one attribute
# test, but the off/on delta between independent runs is noise-prone
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --federation-overhead; then
        ok=1
        break
    fi
    echo "tier1: federation overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: federation overhead smoke FAILED (3 attempts) — idle-link cost over budget" >&2
    exit 1
}

echo "tier1: route microbench smoke (tensor router vs trie, parity gate)"
# the bench itself fails (exit 1) on any kernel/oracle parity mismatch or
# a broken key-shared fan-out; the grep double-checks both batched paths
# really routed with zero mismatches at every table size
timeout -k 10 240 python bench.py --route --quick \
        | tee /tmp/_t1_route.json || {
    rc=$?
    echo "tier1: route smoke FAILED (rc=$rc) — parity mismatch or fan-out error" >&2
    exit "$rc"
}
grep -q '"parity_mismatches": 0' /tmp/_t1_route.json || {
    echo "tier1: route smoke report missing the zero-mismatch parity gate" >&2
    exit 1
}

echo "tier1: tenant soak smoke (~10 s x2 seeds: noisy neighbor, victim SLO intact)"
# the soak itself fails (violation -> exit 1) unless the aggressor is
# rate-gated at the exact token boundary, its held publishes drain in
# FIFO order across every resume, the memory tenant gates and recovers,
# the victim's p99 and both tenant-scoped SLO budgets stay untouched,
# and the tenant-labelled event/firehose streams match exactly; each
# seed runs twice and the decision logs must be byte-identical. Seeds 5
# and 7 sit in different mod-3 classes so the drain-episode counts differ
for seed in 5 7; do
    timeout -k 10 300 python bench.py --tenant --seed "$seed" \
            | tee /tmp/_t1_tenant.json || {
        rc=$?
        echo "tier1: tenant soak smoke FAILED (rc=$rc, seed=$seed) — isolation invariant violation" >&2
        exit "$rc"
    }
    grep -q '"violations": \[\]' /tmp/_t1_tenant.json || {
        echo "tier1: tenant soak report carries violations (seed=$seed)" >&2
        exit 1
    }
    grep -q '"log_sha256": "[0-9a-f]' /tmp/_t1_tenant.json || {
        echo "tier1: tenant soak report missing the decision-log digest (seed=$seed)" >&2
        exit 1
    }
done

echo "tier1: tenant churn smoke (10k define/remove cycles: no registry or byte leak)"
timeout -k 10 300 python bench.py --tenant-churn \
        | tee /tmp/_t1_tenant_churn.json || {
    rc=$?
    echo "tier1: tenant churn smoke FAILED (rc=$rc) — registry/accounting leak" >&2
    exit "$rc"
}
grep -q '"leaked_bytes": 0' /tmp/_t1_tenant_churn.json || {
    echo "tier1: tenant churn leaked accounted bytes" >&2
    exit 1
}

echo "tier1: tenant overhead smoke (5 s x2: quota-less tenant attach <= 2%)"
# same retry rationale as the other overhead gates: the per-publish cost
# of an unrated tenant is one attribute load + None test, but the off/on
# delta from two independent 5 s runs swings +/-10% on a shared box
ok=""
for attempt in 1 2 3; do
    if BENCH_SECONDS=5 timeout -k 10 120 python bench.py --tenant-overhead; then
        ok=1
        break
    fi
    echo "tier1: tenant overhead attempt $attempt over budget, retrying" >&2
done
[ -n "$ok" ] || {
    echo "tier1: tenant overhead smoke FAILED (3 attempts) — tenancy cost over budget" >&2
    exit 1
}
echo "tier1: OK"
