"""Per-priority ready-list fan for x-max-priority queues.

The seed kept priority queues in one deque ordered (priority desc,
offset asc), which made every enqueue an ordered-insert scan — O(depth)
per publish as soon as priorities mix. ``PriorityFan`` fans the ready
list into one deque per priority level and keeps a high-water hint, so
the hot operations (push, dispatch pop, head peek) are O(1) while every
deque-shaped access the queue code performs (iteration, len, peek,
clear, recovery extend) still works unchanged.

Ordering contract (identical to the seed's single deque): iteration and
popleft observe (priority desc, offset asc) — within one band FIFO by
offset, bands served highest first.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Iterable, Iterator


class PriorityFan:
    """Deque-compatible ready list: one band per priority level 0..max.

    ``_hi`` is an upper bound on the highest non-empty band — bumped on
    append, lazily walked down on pop/peek — so the common steady state
    (traffic concentrated on few levels) never scans the full fan.
    """

    __slots__ = ("_bands", "_hi", "_len")

    def __init__(self, max_priority: int, items: Iterable[Any] = ()) -> None:
        self._bands: list[deque] = [deque() for _ in range(max_priority + 1)]
        self._hi = 0
        self._len = 0
        for qm in items:
            self.append(qm)

    # -- hot path ----------------------------------------------------------

    def append(self, qm: Any) -> None:
        """Enqueue by the entry's (already clamped) priority."""
        p = qm.priority
        self._bands[p].append(qm)
        if p > self._hi:
            self._hi = p
        self._len += 1

    def appendleft(self, qm: Any) -> None:
        """Restore an entry to the head of its band — the exact inverse of
        popleft, which the basic_get store-error path relies on."""
        p = qm.priority
        self._bands[p].appendleft(qm)
        if p > self._hi:
            self._hi = p
        self._len += 1

    def popleft(self) -> Any:
        bands = self._bands
        h = self._hi
        while h > 0 and not bands[h]:
            h -= 1
        self._hi = h
        qm = bands[h].popleft()  # empty fan -> IndexError, like deque
        self._len -= 1
        return qm

    # -- requeue -----------------------------------------------------------

    def requeue(self, qm: Any) -> None:
        """Put a redelivered entry back in offset order within its band.

        Requeued offsets are older than the band's tail by construction,
        so the scan runs from the left and usually stops immediately (a
        rejected head goes straight back to the front)."""
        band = self._bands[qm.priority]
        for i, existing in enumerate(band):
            if existing.offset > qm.offset:
                band.insert(i, qm)
                break
        else:
            band.append(qm)
        if qm.priority > self._hi:
            self._hi = qm.priority
        self._len += 1

    # -- deque-shaped surface ----------------------------------------------

    def extend(self, items: Iterable[Any]) -> None:
        for qm in items:
            self.append(qm)

    def clear(self) -> None:
        for band in self._bands:
            band.clear()
        self._hi = 0
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator[Any]:
        # (priority desc, offset asc) — the order the seed's deque held
        return itertools.chain.from_iterable(reversed(self._bands))

    def __getitem__(self, idx: int) -> Any:
        if self._len == 0:
            raise IndexError("fan is empty")
        bands = self._bands
        if idx == 0:
            h = self._hi
            while h > 0 and not bands[h]:
                h -= 1
            self._hi = h
            return bands[h][0]
        if idx == -1:
            for band in bands:
                if band:
                    return band[-1]
        # cold path (nothing in the queue code takes it today): resolve an
        # arbitrary index against the flattened iteration order
        if idx < 0:
            idx += self._len
        if not 0 <= idx < self._len:
            raise IndexError("fan index out of range")
        for band in reversed(bands):
            n = len(band)
            if idx < n:
                return band[idx]
            idx -= n
        raise IndexError("fan index out of range")  # unreachable
