"""Consistent-hash entity ownership.

The placement analogue of the reference's cluster sharding
(shard id = hash(entityId) % 100 spread over nodes, QueueEntity.scala:43-51):
entities map onto a consistent-hash ring of virtual nodes, so membership
changes move only ~1/N of the keyspace (the reference's shard rebalancing,
without a central coordinator).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class HashRing:
    def __init__(self, nodes: Iterable[str] = (), virtual_nodes: int = 64) -> None:
        self.virtual_nodes = virtual_nodes
        self._nodes: set[str] = set()
        self._ring: list[tuple[int, str]] = []
        for node in nodes:
            self._nodes.add(node)
        self._rebuild()

    def _rebuild(self) -> None:
        ring = []
        for node in self._nodes:
            for i in range(self.virtual_nodes):
                ring.append((_hash(f"{node}#{i}"), node))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]

    def set_nodes(self, nodes: Iterable[str]) -> None:
        new = set(nodes)
        if new != self._nodes:
            self._nodes = new
            self._rebuild()

    def add(self, node: str) -> None:
        if node not in self._nodes:
            self._nodes.add(node)
            self._rebuild()

    def remove(self, node: str) -> None:
        if node in self._nodes:
            self._nodes.discard(node)
            self._rebuild()

    @property
    def nodes(self) -> frozenset[str]:
        return frozenset(self._nodes)

    def owner(self, key: str) -> Optional[str]:
        """The node owning a key, or None when the ring is empty."""
        if not self._ring:
            return None
        idx = bisect.bisect_right(self._points, _hash(key)) % len(self._ring)
        return self._ring[idx][1]

    def owner_entity(self, kind: str, vhost: str, name: str) -> Optional[str]:
        # '\x00' can't appear in AMQP short strings, so the key is unambiguous
        return self.owner(f"{kind}\x00{vhost}\x00{name}")

    def preference(self, key: str, count: int) -> list[str]:
        """The first `count` DISTINCT nodes clockwise from the key's point
        (Dynamo-style preference list): [owner, 1st successor, ...]. Used by
        replication to pick follower nodes — successors keep the replica
        placement stable under membership churn (only ~1/N of keys move)."""
        if not self._ring or count <= 0:
            return []
        start = bisect.bisect_right(self._points, _hash(key)) % len(self._ring)
        out: list[str] = []
        for i in range(len(self._ring)):
            node = self._ring[(start + i) % len(self._ring)][1]
            if node not in out:
                out.append(node)
                if len(out) >= count:
                    break
        return out

    def preference_entity(
        self, kind: str, vhost: str, name: str, count: int
    ) -> list[str]:
        return self.preference(f"{kind}\x00{vhost}\x00{name}", count)
