"""AMQP field-table / field-value codec.

Capability parity with the reference's ValueReader/ValueWriter
(chana-mq-base .../model/ValueReader.scala:90-113, ValueWriter.scala:100-159):
the RabbitMQ field-value dialect — tags 'S' longstr, 'I' int32, 'D' decimal,
'T' timestamp, 'F' table, 'A' array, 'b' int8, 'd' double, 'f' float,
'l' int64, 's' int16, 't' bool, 'x' byte-array, 'V' void. Tables and arrays
are length-prefixed (uint32 byte length).

Python mapping: tables are dicts, arrays are lists, 'V' is None, decimals are
decimal.Decimal, timestamps are ints tagged via the Timestamp wrapper on write
(plain ints encode as 'l'; datetime/Timestamp encode as 'T').
"""

from __future__ import annotations

import datetime as _dt
import decimal
import struct
from io import BytesIO
from typing import Any, BinaryIO


class Timestamp(int):
    """An int subclass marking a value to be encoded as an AMQP timestamp ('T')."""


class CodecError(ValueError):
    pass


# ---------------------------------------------------------------------------
# primitive readers
# ---------------------------------------------------------------------------


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    data = stream.read(n)
    if len(data) != n:
        raise CodecError(f"truncated read: wanted {n} bytes, got {len(data)}")
    return data


def read_octet(stream: BinaryIO) -> int:
    return _read_exact(stream, 1)[0]


def read_short(stream: BinaryIO) -> int:
    return struct.unpack(">H", _read_exact(stream, 2))[0]


def read_long(stream: BinaryIO) -> int:
    return struct.unpack(">I", _read_exact(stream, 4))[0]


def read_longlong(stream: BinaryIO) -> int:
    return struct.unpack(">Q", _read_exact(stream, 8))[0]


def read_shortstr(stream: BinaryIO) -> str:
    n = read_octet(stream)
    return _read_exact(stream, n).decode("utf-8")


def read_longstr_bytes(stream: BinaryIO) -> bytes:
    n = read_long(stream)
    return _read_exact(stream, n)


def read_table(stream: BinaryIO) -> dict[str, Any]:
    """Read a length-prefixed field table."""
    size = read_long(stream)
    payload = BytesIO(_read_exact(stream, size))
    table: dict[str, Any] = {}
    while payload.tell() < size:
        key = read_shortstr(payload)
        table[key] = read_field_value(payload)
    return table


def read_array(stream: BinaryIO) -> list[Any]:
    size = read_long(stream)
    payload = BytesIO(_read_exact(stream, size))
    out: list[Any] = []
    while payload.tell() < size:
        out.append(read_field_value(payload))
    return out


def read_field_value(stream: BinaryIO) -> Any:
    tag = _read_exact(stream, 1)
    if tag == b"S":
        return read_longstr_bytes(stream).decode("utf-8", errors="surrogateescape")
    if tag == b"I":
        return struct.unpack(">i", _read_exact(stream, 4))[0]
    if tag == b"D":
        scale = read_octet(stream)
        value = struct.unpack(">i", _read_exact(stream, 4))[0]
        return decimal.Decimal(value).scaleb(-scale)
    if tag == b"T":
        return Timestamp(read_longlong(stream))
    if tag == b"F":
        return read_table(stream)
    if tag == b"A":
        return read_array(stream)
    if tag == b"b":
        return struct.unpack(">b", _read_exact(stream, 1))[0]
    if tag == b"d":
        return struct.unpack(">d", _read_exact(stream, 8))[0]
    if tag == b"f":
        return struct.unpack(">f", _read_exact(stream, 4))[0]
    if tag == b"l":
        return struct.unpack(">q", _read_exact(stream, 8))[0]
    if tag == b"s":
        return struct.unpack(">h", _read_exact(stream, 2))[0]
    if tag == b"t":
        return read_octet(stream) != 0
    if tag == b"x":
        return read_longstr_bytes(stream)
    if tag == b"V":
        return None
    raise CodecError(f"unknown field-value tag: {tag!r}")


# ---------------------------------------------------------------------------
# primitive writers
# ---------------------------------------------------------------------------


def write_octet(out: BinaryIO, value: int) -> None:
    out.write(bytes((value & 0xFF,)))


def write_short(out: BinaryIO, value: int) -> None:
    out.write(struct.pack(">H", value & 0xFFFF))


def write_long(out: BinaryIO, value: int) -> None:
    out.write(struct.pack(">I", value & 0xFFFFFFFF))


def write_longlong(out: BinaryIO, value: int) -> None:
    out.write(struct.pack(">Q", value & 0xFFFFFFFFFFFFFFFF))


def write_shortstr(out: BinaryIO, value: str | None) -> None:
    data = (value or "").encode("utf-8")
    if len(data) > 255:
        raise CodecError(f"shortstr too long: {len(data)} bytes")
    write_octet(out, len(data))
    out.write(data)


def write_longstr(out: BinaryIO, value: str | bytes | None) -> None:
    if value is None:
        value = b""
    # surrogateescape mirrors the read side so a non-UTF-8 longstr received
    # from a peer can be re-encoded verbatim when forwarding.
    data = (
        value.encode("utf-8", errors="surrogateescape")
        if isinstance(value, str)
        else bytes(value)
    )
    write_long(out, len(data))
    out.write(data)


def write_table(out: BinaryIO, table: dict[str, Any] | None) -> None:
    payload = BytesIO()
    for key, value in (table or {}).items():
        write_shortstr(payload, key)
        write_field_value(payload, value)
    data = payload.getvalue()
    write_long(out, len(data))
    out.write(data)


def write_array(out: BinaryIO, values: list[Any]) -> None:
    payload = BytesIO()
    for value in values:
        write_field_value(payload, value)
    data = payload.getvalue()
    write_long(out, len(data))
    out.write(data)


def write_field_value(out: BinaryIO, value: Any) -> None:
    if value is None:
        out.write(b"V")
    elif isinstance(value, bool):
        out.write(b"t")
        write_octet(out, 1 if value else 0)
    elif isinstance(value, Timestamp):
        out.write(b"T")
        write_longlong(out, int(value))
    elif isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            out.write(b"I")
            out.write(struct.pack(">i", value))
        else:
            out.write(b"l")
            out.write(struct.pack(">q", value))
    elif isinstance(value, float):
        out.write(b"d")
        out.write(struct.pack(">d", value))
    elif isinstance(value, str):
        out.write(b"S")
        write_longstr(out, value)
    elif isinstance(value, (bytes, bytearray, memoryview)):
        out.write(b"x")
        write_longstr(out, bytes(value))
    elif isinstance(value, decimal.Decimal):
        out.write(b"D")
        # AMQP decimal = scale octet + int32, decoded as int_val * 10^-scale.
        # A positive decimal exponent (e.g. 1E+2) needs scale 0, not a negative
        # scale, so the value is expanded to an integer instead.
        scale = max(0, -value.as_tuple().exponent)
        write_octet(out, scale)
        out.write(struct.pack(">i", int(value.scaleb(scale))))
    elif isinstance(value, _dt.datetime):
        out.write(b"T")
        write_longlong(out, int(value.timestamp()))
    elif isinstance(value, dict):
        out.write(b"F")
        write_table(out, value)
    elif isinstance(value, (list, tuple)):
        out.write(b"A")
        write_array(out, list(value))
    else:
        raise CodecError(f"cannot encode field value of type {type(value).__name__}")


def encode_table(table: dict[str, Any] | None) -> bytes:
    out = BytesIO()
    write_table(out, table)
    return out.getvalue()


def decode_table(data: bytes) -> dict[str, Any]:
    return read_table(BytesIO(data))
