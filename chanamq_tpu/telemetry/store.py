"""Fixed-slot per-entity timeseries rings.

One 3-D numpy buffer holds every entity's ring: ``(slots, ticks,
fields)``. A slot is leased to an entity (a queue or a connection) on
first sight and recycled when the entity disappears; beyond capacity new
entities are *dropped from sampling* (counted, never resized) so memory
stays fixed no matter how many queues a tenant declares — the
data-parallel batch-over-actors idea (PAPERS.md, OpenCL Actors): the
alert engine and the top-K selector read the whole entity population as
one matrix operation instead of per-entity loops.

Plain numpy, no JAX: writers run on the broker's event loop each sampler
tick; readers (admin handlers, the forecaster feature tap) take copies.
"""

from __future__ import annotations

from typing import Hashable, Optional

import numpy as np

# per-queue series, one value per field per tick. Rates are per-second
# deltas of the queue's monotonic n_published/n_delivered/n_acked
# counters; the rest are instantaneous gauges.
QUEUE_FIELDS: tuple[str, ...] = (
    "publish_rate", "deliver_rate", "ack_rate",
    "depth", "unacked", "consumers", "ready_bytes",
)

# per-connection series. credit is the remaining consumer-prefetch
# budget summed over the connection's channels (0 when unlimited).
CONN_FIELDS: tuple[str, ...] = (
    "publish_rate", "deliver_rate", "ack_rate",
    "channels", "unacked", "credit",
)


class EntityRings:
    """Slot-leased timeseries rings over one shared (slots, ticks, F) buffer.

    Single-writer (the sampler tick on the event loop). All active slots
    are written every tick, so per-slot cursors advance in lockstep; a
    per-slot count still tracks how much history each entity has (slots
    leased mid-run have shorter series).
    """

    def __init__(self, slots: int, ticks: int, fields: tuple[str, ...]) -> None:
        assert slots > 0 and ticks > 1
        self.fields = fields
        self.slots = slots
        self.ticks = ticks
        self._buf = np.zeros((slots, ticks, len(fields)), dtype=np.float32)
        self._index: dict[Hashable, int] = {}
        self._free = list(range(slots - 1, -1, -1))  # pop() leases slot 0 first
        self._next = np.zeros(slots, dtype=np.int64)
        self._count = np.zeros(slots, dtype=np.int64)
        self.evicted = 0   # slots recycled because their entity went away
        self.dropped = 0   # entities seen while no slot was free

    def __len__(self) -> int:
        return len(self._index)

    def lease(self, key: Hashable) -> Optional[int]:
        """Slot for key, leasing a free one on first sight. None = full
        (the entity is invisible to telemetry until a slot frees up)."""
        slot = self._index.get(key)
        if slot is not None:
            return slot
        if not self._free:
            self.dropped += 1
            return None
        slot = self._free.pop()
        self._index[key] = slot
        self._buf[slot] = 0.0
        self._next[slot] = 0
        self._count[slot] = 0
        return slot

    def retire(self, key: Hashable) -> None:
        """Entity disappeared: recycle its slot."""
        slot = self._index.pop(key, None)
        if slot is not None:
            self._free.append(slot)
            self.evicted += 1

    def retire_absent(self, live: set) -> None:
        """Recycle every slot whose entity is not in the live set."""
        for key in [k for k in self._index if k not in live]:
            self.retire(key)

    def push(self, slot: int, vec: np.ndarray) -> None:
        self._buf[slot, self._next[slot]] = vec
        self._next[slot] = (self._next[slot] + 1) % self.ticks
        self._count[slot] += 1

    # -- matrix reads (alert engine / top-K) -------------------------------

    def keys(self) -> list:
        """Active entities, sorted for deterministic evaluation order."""
        return sorted(self._index)

    def latest_matrix(self) -> tuple[list, np.ndarray]:
        """(keys, (E, F) matrix) of each active entity's newest vector."""
        keys = self.keys()
        if not keys:
            return keys, np.zeros((0, len(self.fields)), dtype=np.float32)
        slots = np.array([self._index[k] for k in keys])
        idx = (self._next[slots] - 1) % self.ticks
        return keys, self._buf[slots, idx].copy()

    def delta_matrix(self, window: int) -> tuple[list, np.ndarray]:
        """(keys, (E, F) matrix) of newest-minus-(window-ticks-ago) per
        entity — the growth signal. Entities with less history than the
        window compare against their oldest sample; entities with a
        single sample report zero growth."""
        keys = self.keys()
        if not keys:
            return keys, np.zeros((0, len(self.fields)), dtype=np.float32)
        slots = np.array([self._index[k] for k in keys])
        count = self._count[slots]
        back = np.minimum(np.maximum(count - 1, 0), window)
        newest = (self._next[slots] - 1) % self.ticks
        oldest = (self._next[slots] - 1 - back) % self.ticks
        return keys, (self._buf[slots, newest] - self._buf[slots, oldest])

    # -- per-entity reads (drilldown / forecaster features) ----------------

    def series(self, key: Hashable, window: int) -> Optional[np.ndarray]:
        """The newest <= window vectors for key, oldest first (copy)."""
        slot = self._index.get(key)
        if slot is None:
            return None
        n = int(min(self._count[slot], self.ticks, window))
        if n == 0:
            return np.zeros((0, len(self.fields)), dtype=np.float32)
        end = int(self._next[slot])
        start = (end - n) % self.ticks
        if start < end:
            return self._buf[slot, start:end].copy()
        return np.concatenate(
            [self._buf[slot, start:], self._buf[slot, :end]])

    def stats(self) -> dict:
        return {
            "entities": len(self._index),
            "slots": self.slots,
            "ticks": self.ticks,
            "evicted": self.evicted,
            "dropped": self.dropped,
        }
