"""Message-body passivation / store hydration.

The reference pages inactive message bodies out to the store and
Promise-loads them back on Get (MessageEntity.scala:82-102 passivation timer
at :168-198, knob chana.mq.message.inactive). Here the analogue is
depth-based: beyond the per-queue resident watermark
(chana.mq.queue.max-resident), durable+persistent bodies are dropped from
RAM and hydrated back from the store before delivery — so a deep backlog in
a consumerless durable queue holds bounded memory.
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)
WATERMARK = 8


@pytest.fixture
def db_path(tmp_path):
    return str(tmp_path / "broker.db")


async def start_server(db_path, max_resident=WATERMARK):
    broker = Broker(store=SqliteStore(db_path), queue_max_resident=max_resident)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    return srv


def resident_bodies(queue):
    return [qm for qm in queue.messages if qm.message.body is not None]


async def test_deep_backlog_bounded_then_consumed_in_order(db_path):
    """The VERDICT round-3 acceptance test: publish >> watermark persistent
    bodies into a consumerless durable queue, assert bounded resident bytes,
    then consume everything in order with bodies intact."""
    srv = await start_server(db_path)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("deep_q", durable=True)

    n = 100
    body_size = 1024
    for i in range(n):
        ch.basic_publish((b"%04d" % i) + b"x" * (body_size - 4),
                         routing_key="deep_q", properties=PERSISTENT)
    await ch.wait_unconfirmed_below(1)

    queue = srv.broker.vhosts["/"].queues["deep_q"]
    assert len(queue.messages) == n
    resident = resident_bodies(queue)
    assert len(resident) <= WATERMARK + 1
    # the broker-level gauge reflects the bound (per-queue resident bodies
    # plus nothing else alive in this test)
    assert srv.broker.resident_bytes <= (WATERMARK + 1) * (body_size + 64)
    # passivated entries kept their QoS/store bookkeeping size
    assert all(qm.body_size == body_size for qm in queue.messages)

    # now consume everything: hydration must reattach bodies in order
    received = []
    done = asyncio.get_event_loop().create_future()

    def cb(msg):
        received.append(msg)
        ch.basic_ack(msg.delivery_tag)
        if len(received) >= n and not done.done():
            done.set_result(None)

    await ch.basic_consume("deep_q", cb)
    await asyncio.wait_for(done, 30)
    assert [m.body[:4] for m in received] == [b"%04d" % i for i in range(n)]
    assert all(len(m.body) == body_size for m in received)
    assert all(m.properties.delivery_mode == 2 for m in received)

    await c.close()
    await srv.stop()


async def test_basic_get_hydrates_passivated_head(db_path):
    srv = await start_server(db_path, max_resident=2)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("get_q", durable=True)
    for i in range(10):
        ch.basic_publish(b"msg-%d" % i, routing_key="get_q",
                         properties=PERSISTENT)
    await ch.wait_unconfirmed_below(1)
    queue = srv.broker.vhosts["/"].queues["get_q"]
    assert len(resident_bodies(queue)) <= 3
    for i in range(10):
        m = await ch.basic_get("get_q", no_ack=True)
        assert m is not None and m.body == b"msg-%d" % i
    assert await ch.basic_get("get_q") is None
    await c.close()
    await srv.stop()


async def test_dead_blob_skipped_not_crashed(db_path):
    """A passivated entry whose blob vanished from the store (manual delete /
    external TTL) is marked dead and skipped, not delivered as a crash."""
    srv = await start_server(db_path, max_resident=2)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("dead_q", durable=True)
    for i in range(6):
        ch.basic_publish(b"msg-%d" % i, routing_key="dead_q",
                         properties=PERSISTENT)
    await ch.wait_unconfirmed_below(1)
    queue = srv.broker.vhosts["/"].queues["dead_q"]
    # kill the blob of the first PASSIVATED entry behind the resident head
    victim = next(qm for qm in queue.messages if qm.message.body is None)
    await srv.broker.store.delete_message(victim.message.id)
    await srv.broker.store.flush()

    got = []
    while True:
        m = await ch.basic_get("dead_q", no_ack=True)
        if m is None:
            break
        got.append(m.body)
    expected = [b"msg-%d" % i for i in range(6)
                if i != victim.offset - 1]
    assert got == expected
    await c.close()
    await srv.stop()


@pytest.mark.parametrize("meta_chunk", [None, 7])
async def test_recovery_respects_resident_watermark(db_path, monkeypatch,
                                                    meta_chunk):
    """Restarting over a deep durable backlog must not reload every body
    into RAM — and must still deliver everything in order afterwards.

    meta_chunk=7 additionally forces recovery's metadata paging
    (RECOVER_META_CHUNK) across several chunk boundaries over the 30-deep
    backlog (VERDICT r3 weak #7: the transient meta dict must not
    double-hold the whole backlog; the reference streams per-entity via
    selectQueue)."""
    srv = await start_server(db_path, max_resident=4)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("rec_q", durable=True)
    for i in range(30):
        ch.basic_publish(b"m-%02d" % i, routing_key="rec_q",
                         properties=PERSISTENT)
    await ch.wait_unconfirmed_below(1)
    await c.close()
    await srv.stop()

    if meta_chunk is not None:
        monkeypatch.setattr(Broker, "RECOVER_META_CHUNK", meta_chunk)
    srv2 = await start_server(db_path, max_resident=4)
    queue = srv2.broker.vhosts["/"].queues["rec_q"]
    assert len(queue.messages) == 30
    assert len(resident_bodies(queue)) <= 4

    c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
    ch2 = await c2.channel()
    received = []
    done = asyncio.get_event_loop().create_future()

    def cb(msg):
        received.append(msg)
        ch2.basic_ack(msg.delivery_tag)
        if len(received) >= 30 and not done.done():
            done.set_result(None)

    await ch2.basic_consume("rec_q", cb)
    await asyncio.wait_for(done, 30)
    assert [m.body for m in received] == [b"m-%02d" % i for i in range(30)]
    await c2.close()
    await srv2.stop()


async def test_fanout_passivation_shares_body_safely(db_path):
    """Advisor round-3 high: a persistent message fanned out to multiple
    durable queues must survive one queue passivating the shared body —
    body_size is computed once at publish, and the sibling queue hydrates
    from the store like any passivated entry."""
    srv = await start_server(db_path, max_resident=4)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.confirm_select()
    await ch.exchange_declare("fan_x", "fanout", durable=True)
    await ch.queue_declare("fan_a", durable=True)
    await ch.queue_declare("fan_b", durable=True)
    await ch.queue_bind("fan_a", "fan_x", "")
    await ch.queue_bind("fan_b", "fan_x", "")

    n = 12  # well past max_resident=4: the advisor repro crashed on the 5th
    for i in range(n):
        ch.basic_publish(b"fan-%02d" % i, exchange="fan_x", routing_key="",
                         properties=PERSISTENT)
    await ch.wait_unconfirmed_below(1)

    qa = srv.broker.vhosts["/"].queues["fan_a"]
    qb = srv.broker.vhosts["/"].queues["fan_b"]
    assert len(qa.messages) == n and len(qb.messages) == n
    # every entry carries the true body size even where the shared body was
    # paged out by the sibling queue
    assert all(qm.body_size == 6 for qm in qa.messages)
    assert all(qm.body_size == 6 for qm in qb.messages)

    # both queues drain fully, in order, with hydrated bodies
    for qname in ("fan_a", "fan_b"):
        got = []
        while True:
            m = await ch.basic_get(qname, no_ack=True)
            if m is None:
                break
            got.append(m.body)
        assert got == [b"fan-%02d" % i for i in range(n)]
    await c.close()
    await srv.stop()


async def test_transient_bodies_page_out_and_drain_in_order(db_path):
    """VERDICT r3 #2b: transient bodies also page out past the watermark
    (the reference's ActiveCheckTick persists unconditionally before
    passivating, MessageEntity.scala:171-186) — bounded RAM, full in-order
    drain, and no durability promise attaches."""
    srv = await start_server(db_path, max_resident=2)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("mix_q", durable=True)
    for i in range(10):
        ch.basic_publish(b"t-%d" % i, routing_key="mix_q")  # delivery_mode 1
    await asyncio.sleep(0.2)
    queue = srv.broker.vhosts["/"].queues["mix_q"]
    assert len(queue.messages) == 10
    assert len(resident_bodies(queue)) <= 3  # deep tail paged out
    # paged, not persisted: no durability promise
    assert all(not qm.message.persisted for qm in queue.messages)
    got = []
    while True:
        m = await ch.basic_get("mix_q", no_ack=True)
        if m is None:
            break
        got.append(m.body)
    assert got == [b"t-%d" % i for i in range(10)]
    await c.close()
    await srv.stop()


async def test_paged_transients_not_resurrected_by_recovery(db_path):
    """Transient messages stay transient: paged-out blobs must not come
    back after a restart (the reference's HA contract — transients die with
    the node), and a clean shutdown removes the paged blobs themselves."""
    srv = await start_server(db_path, max_resident=2)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("tr_q", durable=True)
    for i in range(8):
        ch.basic_publish(b"x-%d" % i, routing_key="tr_q")
    await asyncio.sleep(0.2)
    queue = srv.broker.vhosts["/"].queues["tr_q"]
    paged_ids = [qm.message.id for qm in queue.messages if qm.message.paged]
    assert paged_ids  # some bodies really were paged out
    await c.close()
    await srv.stop()

    srv2 = await start_server(db_path, max_resident=2)
    queue2 = srv2.broker.vhosts["/"].queues["tr_q"]
    assert len(queue2.messages) == 0  # transients died with the process
    # clean shutdown deleted the paged blobs (no orphan accumulation)
    stored = await srv2.broker.store.select_messages(paged_ids)
    assert stored == {}
    await srv2.stop()


async def test_transient_paged_body_visible_to_inline_basic_get():
    """A paged transient body written fire-and-forget must be readable with
    ZERO event-loop yields in between: MemoryStore (the default, no --store)
    applies writes at call time, so a pipelined publish-past-watermark
    followed immediately by basic.get can't miss the blob and silently drop
    the message."""
    from chanamq_tpu.store.memory import MemoryStore

    broker = Broker(store=MemoryStore(), queue_max_resident=2)
    await broker.start()
    try:
        await broker.declare_queue("/", "q", durable=False)
        for i in range(6):
            await broker.publish(
                "/", "", "q", BasicProperties(delivery_mode=1), b"m%d" % i)
        queue = broker.vhost("/").queues["q"]
        # tail entries are paged (body in store only)
        assert any(qm.message.body is None for qm in queue.messages)
        got = []
        # same task, no awaits other than basic_get itself (whose store
        # read must see the eager write)
        for _ in range(6):
            qm = await queue.basic_get()
            assert qm is not None, f"paged message lost after {got}"
            got.append(bytes(qm.message.body))
            broker.unrefer(qm.message)
        assert got == [b"m%d" % i for i in range(6)]
    finally:
        await broker.stop()


async def test_basic_get_drain_does_not_retain_hydrated_bodies():
    """basic_get hydrates without the dispatch-path collector: the
    passivated deque must still shed settled entries, or a publish-burst →
    get-drain cycle retains every hydrated body forever (invisible to
    resident_bytes)."""
    from chanamq_tpu.store.memory import MemoryStore

    broker = Broker(store=MemoryStore(), queue_max_resident=2)
    await broker.start()
    try:
        await broker.declare_queue("/", "q", durable=False)
        queue = broker.vhost("/").queues["q"]
        for cycle in range(3):
            for i in range(20):
                await broker.publish(
                    "/", "", "q", BasicProperties(delivery_mode=1), b"x" * 512)
            while True:
                qm = await queue.basic_get()
                if qm is None:
                    break
                broker.unrefer(qm.message)
            assert len(queue._passivated) == 0, (cycle, len(queue._passivated))
        assert broker.resident_bytes == 0
    finally:
        await broker.stop()


async def test_expired_passivated_entries_leave_the_deque():
    """A consumerless TTL'd queue: expiry must prune the passivated deque
    too, or each burst pins dead Message objects (properties + header_raw)
    forever, invisible to resident_bytes."""
    from chanamq_tpu.store.memory import MemoryStore

    broker = Broker(store=MemoryStore(), queue_max_resident=2,
                    message_sweep_interval_s=0)
    await broker.start()
    try:
        await broker.declare_queue("/", "q", durable=False,
                                   arguments={"x-message-ttl": 30})
        queue = broker.vhost("/").queues["q"]
        for i in range(20):
            await broker.publish(
                "/", "", "q", BasicProperties(delivery_mode=1), b"x" * 256)
        assert len(queue._passivated) > 0
        await asyncio.sleep(0.1)  # everything expires
        queue._expire_head()
        assert len(queue.messages) == 0
        assert len(queue._passivated) == 0
        assert broker.resident_bytes == 0
    finally:
        await broker.stop()


async def test_passivated_messages_dead_letter_with_hydrated_bodies(tmp_path):
    """A passivated (body paged out) message that expires in a DLX'd queue
    is hydrated from the store before forwarding: the dead-letter queue
    receives the FULL body, not an empty shell."""
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.sqlite import SqliteStore

    broker = Broker(store=SqliteStore(str(tmp_path / "pdlx.db")),
                    queue_max_resident=4, message_sweep_interval_s=0.1)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.exchange_declare("pdlx_ex", "fanout")
        await ch.queue_declare("pdlx_dlq")
        await ch.queue_bind("pdlx_dlq", "pdlx_ex", "")
        await ch.queue_declare("pdlx_q", arguments={
            "x-message-ttl": 300, "x-dead-letter-exchange": "pdlx_ex"})
        bodies = [b"deep-%03d" % i + b"x" * 100 for i in range(16)]
        for body in bodies:
            ch.basic_publish(body, routing_key="pdlx_q")
        # beyond max_resident=4 the tail pages out; wait for TTL + sweep
        await asyncio.sleep(0.1)
        assert srv.broker.resident_bytes < sum(len(b) for b in bodies)
        got = []
        deadline = asyncio.get_event_loop().time() + 8
        while (len(got) < len(bodies)
               and asyncio.get_event_loop().time() < deadline):
            m = await ch.basic_get("pdlx_dlq", no_ack=True)
            if m is None:
                await asyncio.sleep(0.05)
                continue
            got.append(m)
        assert sorted(m.body for m in got) == sorted(bodies)
        for m in got:
            assert m.properties.headers["x-death"][0]["reason"] == "expired"
        await c.close()
    finally:
        await srv.stop()


async def test_lazy_queue_mode_pages_aggressively(tmp_path):
    """x-queue-mode=lazy (RabbitMQ lazy queues, mapped onto passivation):
    bodies page out beyond a small resident head regardless of the
    broker-wide watermark, and consumption still delivers everything in
    order with full bodies."""
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.sqlite import SqliteStore

    # broker-wide passivation effectively off (huge watermark)
    broker = Broker(store=SqliteStore(str(tmp_path / "lazy.db")),
                    queue_max_resident=10**9)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("lazy_q", arguments={"x-queue-mode": "lazy"})
        from chanamq_tpu.broker.entities import Queue

        n = Queue.LAZY_RESIDENT + 200
        body = b"z" * 256
        for i in range(n):
            ch.basic_publish(i.to_bytes(4, "big") + body,
                             routing_key="lazy_q")
        await asyncio.sleep(0.2)
        # the deep tail paged out: resident bytes far below the full backlog
        assert broker.resident_bytes <= (Queue.LAZY_RESIDENT + 8) * 300, \
            broker.resident_bytes
        # ...and a plain (non-lazy) queue with the same broker keeps all:
        # assert on the DELTA so the lazy queue's resident head can't
        # satisfy the check by itself
        resident_before_eager = broker.resident_bytes
        await ch.queue_declare("eager_q")
        for i in range(50):
            ch.basic_publish(body, routing_key="eager_q")
        await asyncio.sleep(0.1)
        assert broker.resident_bytes - resident_before_eager >= 50 * 256
        # drain the lazy queue fully, in order, bodies intact
        got = 0
        deadline = asyncio.get_event_loop().time() + 15
        while got < n and asyncio.get_event_loop().time() < deadline:
            m = await ch.basic_get("lazy_q", no_ack=True)
            if m is None:
                await asyncio.sleep(0.02)
                continue
            assert int.from_bytes(m.body[:4], "big") == got
            assert m.body[4:] == body
            got += 1
        assert got == n
        await c.close()
    finally:
        await srv.stop()


async def test_queue_mode_validation():
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.client.client import ChannelClosedError

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        with pytest.raises(ChannelClosedError) as exc_info:
            await ch.queue_declare("bad_mode_q",
                                   arguments={"x-queue-mode": "warp"})
        assert exc_info.value.reply_code == 406
        ch2 = await c.channel()
        await ch2.queue_declare("ok_mode_q",
                                arguments={"x-queue-mode": "default"})
        await c.close()
    finally:
        await srv.stop()


async def test_lazy_queue_recovery_honors_override(tmp_path):
    """Recovery of a durable lazy queue loads only the lazy resident head
    even when the broker-wide watermark is huge (the per-queue override
    applies at restart, not just at push time)."""
    from chanamq_tpu.broker.broker import Broker
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.broker.entities import Queue
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.sqlite import SqliteStore

    db = str(tmp_path / "lazyrec.db")
    broker = Broker(store=SqliteStore(db), queue_max_resident=10**9)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    n = Queue.LAZY_RESIDENT + 300
    body = b"r" * 256
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.queue_declare("lzr_q", durable=True,
                               arguments={"x-queue-mode": "lazy"})
        for i in range(n):
            ch.basic_publish(i.to_bytes(4, "big") + body,
                             routing_key="lzr_q",
                             properties=BasicProperties(delivery_mode=2))
        ch2 = await c.channel()
        await ch2.queue_declare("lzr_q", passive=True)  # ordering barrier
        await c.close()
    finally:
        await srv.stop()

    broker2 = Broker(store=SqliteStore(db), queue_max_resident=10**9)
    srv2 = BrokerServer(broker=broker2, host="127.0.0.1", port=0,
                        heartbeat_s=0)
    await srv2.start()
    try:
        # only ~the lazy head came back resident
        assert broker2.resident_bytes <= (Queue.LAZY_RESIDENT + 8) * 300, \
            broker2.resident_bytes
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch3 = await c2.channel()
        ok = await ch3.queue_declare("lzr_q", durable=True, passive=True,
                                     arguments={"x-queue-mode": "lazy"})
        assert ok.message_count == n
        # full drain, in order, bodies hydrated
        for i in range(n):
            m = None
            for _ in range(100):
                m = await ch3.basic_get("lzr_q", no_ack=True)
                if m is not None:
                    break
                await asyncio.sleep(0.02)
            assert m is not None and int.from_bytes(m.body[:4], "big") == i
        await c2.close()
    finally:
        await srv2.stop()
