"""OpenTelemetry interop for the trace subsystem (ISSUE 20).

Three pieces, all optional and all riding the existing fixed-slot
traces (chanamq_tpu/trace/):

- :mod:`context` — W3C trace-context parsing/formatting plus the
  deterministic id derivations that let a forced sample mint span ids
  without touching the seeded sampling RNG;
- :mod:`export` — the OTLP/HTTP JSON render (``ResourceSpans``) and the
  background :class:`~chanamq_tpu.otel.export.OtelExporter` service
  behind ``chana.mq.otel.*``;
- Prometheus exemplars are rendered by rest/admin from the same slow
  ring (scrape ``/metrics?format=openmetrics``).

Nothing here is imported on the hot path: the trace runtime imports only
the pure helpers in :mod:`context`, and the exporter hooks trace
completion (already off the per-message path).
"""

from __future__ import annotations

from .context import (  # noqa: F401  (package API)
    W3CContext, derive_span_id, derive_trace_id, extract,
    format_traceparent, parse_traceparent, stamp_headers,
)
