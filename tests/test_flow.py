"""Overload-protection ladder (chanamq_tpu/flow/): watermark hysteresis,
per-connection publish credit, Channel.Flow wire behavior, lazy body
paging, stage-4 publish refusal, readiness coupling, and the two scripted
scenarios (overload soak, connection churn).

The ladder tests drive pressure synchronously through the accountant's
``chaos`` component (``broker.flow.add("chaos", N)``): with no chaos plan
installed the sweep's _flow_tick leaves that component alone, so stage
transitions happen at a deterministic point in the test instead of riding
wall-clock tick timing.
"""

import asyncio

import pytest

from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.chaos.plan import FaultPlan, FaultRule
from chanamq_tpu.chaos.runtime import ChaosRuntime
from chanamq_tpu.chaos.soak import (
    OVERLOAD_ALERT_RULES,
    run_connection_churn,
    run_overload_soak,
)
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.flow import (
    MemoryAccountant,
    STAGE_CLUSTER,
    STAGE_NORMAL,
    STAGE_PAGE,
    STAGE_REFUSE,
    STAGE_THROTTLE,
)
from chanamq_tpu.store.memory import MemoryStore

pytestmark = pytest.mark.asyncio


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError("condition not reached in time")
        await asyncio.sleep(interval)


async def start_broker(**kwargs):
    broker = Broker(store=MemoryStore(), **kwargs)
    srv = BrokerServer(broker=broker, host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    return broker, srv


# ---------------------------------------------------------------------------
# accountant unit behavior
# ---------------------------------------------------------------------------

async def test_accountant_thresholds_hysteresis_single_jump():
    """Derived thresholds, hysteresis gaps, and the one-listener-call-per-
    transition contract (a burst that crosses three stages fires ONE
    (old, new) event, not a cascade)."""
    acc = MemoryAccountant(high_watermark=1000, low_watermark=800)
    # derived: hard=2*high, refuse=0.9*hard, page=0.6*high,
    # cluster=(high+refuse)//2
    assert acc.enter == (0, 600, 1000, 1400, 1800)
    assert acc.hard_limit == 2000
    # every exit threshold scales its enter by low/high (stage 2 keeps the
    # exact legacy block-above-high / unblock-at-low contract)
    assert acc.exit == tuple(e * 800 // 1000 for e in acc.enter)

    events = []
    acc.listeners.append(lambda old, new: events.append((old, new)))

    acc.add("chaos", 1900)  # one burst past every enter threshold
    assert acc.stage == STAGE_REFUSE
    assert events == [(0, 4)]

    # hysteresis: below enter[4] but above exit[4]=1440 -> no flap
    acc.add("chaos", -200)
    assert acc.stage == STAGE_REFUSE and len(events) == 1

    # at/below exit[4] but above exit[3]=1120 -> exactly one step down
    acc.add("chaos", -300)
    assert acc.stage == STAGE_CLUSTER
    assert events[-1] == (4, 3)

    # full drain cascades to normal in ONE listener call
    acc.add("chaos", -1400)
    assert acc.stage == STAGE_NORMAL
    assert events[-1] == (3, 0)
    assert len(events) == 3
    assert acc.peak_total == 1900


async def test_accountant_held_excluded_from_gate_but_counted():
    """Parked publish bytes must never feed the gate that parked them
    (deadlock), but they ARE real memory: reported in total and peak."""
    acc = MemoryAccountant(high_watermark=1000)
    acc.add("held", 5000)  # way past every enter threshold
    assert acc.stage == STAGE_NORMAL
    assert acc.total == 5000 and acc.peak_total == 5000
    # non-held bytes still escalate normally on top
    acc.add("bodies", 1100)
    assert acc.stage == STAGE_THROTTLE
    acc.add("bodies", -1100)
    acc.add("held", -5000)
    assert acc.stage == STAGE_NORMAL and acc.total == 0


async def test_accountant_cluster_stall_bounded():
    """Stage >= 3 parks cluster pushes on a BOUNDED wait (pushback, not
    deadlock); below stage 3 the wait returns immediately."""
    acc = MemoryAccountant(high_watermark=1000)
    acc.add("chaos", 1500)  # cluster enter = 1400
    assert acc.stage == STAGE_CLUSTER
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    await acc.cluster_stall(timeout=0.1)  # nothing releases it: times out
    assert loop.time() - t0 >= 0.09
    acc.add("chaos", -1500)
    assert acc.stage == STAGE_NORMAL
    t0 = loop.time()
    await acc.cluster_stall(timeout=5.0)  # event set: immediate
    assert loop.time() - t0 < 0.5


async def test_chaos_pressure_rule_window_deterministic():
    """A pressure rule is armed on matching invocations (after, until] and
    nowhere else; non-matching sites don't consume the window."""
    plan = FaultPlan(5, [FaultRule(
        name="mem", kind="pressure", sites=["flow.tick"],
        after=2, until=5, inflate_bytes=777)])
    rt = ChaosRuntime(plan)
    assert rt.decide("rpc.call") is None  # wrong site: no invocation burned
    fires = [rt.decide("flow.tick") for _ in range(8)]
    hits = [f for f in fires if f is not None]
    assert [f is not None for f in fires] == [
        False, False, True, True, True, False, False, False]
    assert all(f.kind == "pressure" and f.inflate_bytes == 777 for f in hits)


# ---------------------------------------------------------------------------
# wire behavior: channel.flow, publish credit, stage-4 refusal
# ---------------------------------------------------------------------------

async def test_channel_flow_stop_resume_on_wire():
    """Satellite (c): crossing the throttle stage sends Channel.Flow(
    active=false) to publisher channels only; deliveries and redeliveries
    keep flowing while throttled; dropping below the exit threshold sends
    Flow(active=true) and publishing works end-to-end again."""
    broker, srv = await start_broker(flow_high_watermark=64 * 1024)
    pub = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    pch = await pub.channel()
    await pch.queue_declare("fl_q")
    con = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cch = await con.channel()
    received = []

    for i in range(5):
        pch.basic_publish(b"m%d" % i, routing_key="fl_q")
    queue = broker.vhosts["/"].queues["fl_q"]
    await wait_for(lambda: len(queue.messages) == 5)

    await cch.basic_qos(prefetch_count=10)
    await cch.basic_consume("fl_q", received.append, no_ack=False)
    await wait_for(lambda: len(received) == 5)

    # throttle: 80 KiB sits between enter[2]=64KiB and enter[3]
    broker.flow.add("chaos", 80 * 1024)
    assert broker.flow.stage == STAGE_THROTTLE
    await wait_for(lambda: pch.flow_events == [False])
    assert pch.flow_active is False
    # consumer-only connection is never flow-stopped (it IS the drain)
    assert cch.flow_events == [] and cch.flow_active is True

    # deliveries keep moving while throttled: requeue one -> redelivery
    cch.basic_nack(received[0].delivery_tag, requeue=True)
    await wait_for(lambda: len(received) == 6)
    assert received[5].redelivered and received[5].body == received[0].body
    for m in received[1:]:
        cch.basic_ack(m.delivery_tag)

    # drain the pressure below exit[2]: resume goes out to the survivors
    broker.flow.add("chaos", -80 * 1024)
    assert broker.flow.stage == STAGE_NORMAL
    await wait_for(lambda: pch.flow_events == [False, True])
    assert pch.flow_active is True
    assert broker.metrics.flow_throttles == 1
    assert broker.metrics.flow_resumes == 1

    pch.basic_publish(b"after", routing_key="fl_q")
    await wait_for(lambda: len(received) == 7)
    assert received[6].body == b"after"

    await pub.close()
    await con.close()
    await srv.stop()


async def test_publish_credit_spends_exactly_then_holds():
    """chana.mq.flow.publish-credit: the first gated publishes spend a
    byte allowance (body + flat overhead each) before the hard hold
    engages — credit 8192 at cost 2048/publish admits exactly 4."""
    broker, srv = await start_broker(
        flow_high_watermark=64 * 1024, flow_publish_credit=8192)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("cr_q")
    ch.basic_publish(b"warm", routing_key="cr_q")  # marks the connection
    queue = broker.vhosts["/"].queues["cr_q"]      # as a publisher
    await wait_for(lambda: len(queue.messages) == 1)

    broker.flow.add("chaos", 80 * 1024)  # close the gate (stage 2)
    assert broker.blocked

    body = b"z" * 1536  # held cost = 1536 + 512 overhead = 2048
    for _ in range(10):
        ch.basic_publish(body, routing_key="cr_q")
    # exactly 4 spend credit and execute; 5..10 park at the gate. The
    # client's auto-FlowOk (answering the throttle's Channel.Flow) rides
    # the same channel and parks FIFO behind them at flat overhead cost.
    await wait_for(lambda: broker.held_bytes == 6 * 2048 + 512)
    assert len(queue.messages) == 1 + 4
    await asyncio.sleep(0.2)  # no slow leak past the exhausted credit
    assert len(queue.messages) == 1 + 4

    # reopen: the held tail releases, everything lands, gauge drains
    broker.flow.add("chaos", -80 * 1024)
    await wait_for(lambda: len(queue.messages) == 11)
    await wait_for(lambda: broker.held_bytes == 0)
    assert broker.metrics.flow_hold_releases == 1
    assert broker.metrics.flow_hold_wait_ns > 0

    got = [await ch.basic_get("cr_q", no_ack=True) for _ in range(11)]
    assert [m.body for m in got] == [b"warm"] + [body] * 10
    await c.close()
    await srv.stop()


async def test_stage4_refuses_fresh_publishes_consumers_drain():
    """Past the refuse watermark a fresh publish gets a 406 channel close
    instead of parking (holding more bodies would march accounted memory
    toward the hard limit); consumers keep draining; once pressure drops
    a new channel publishes normally."""
    broker, srv = await start_broker(flow_high_watermark=64 * 1024)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("rf_q")
    for i in range(3):
        ch.basic_publish(b"pre%d" % i, routing_key="rf_q")
    queue = broker.vhosts["/"].queues["rf_q"]
    await wait_for(lambda: len(queue.messages) == 3)

    # refuse enter = 0.9 * hard = 117964 for high=64KiB; 125000 crosses it
    # while staying under the 128KiB hard limit
    broker.flow.add("chaos", 125_000)
    assert broker.flow.stage == STAGE_REFUSE
    assert broker.flow_refusing

    ch.basic_publish(b"refused", routing_key="rf_q")
    await wait_for(lambda: ch.closed)
    assert ch.close_reason.reply_code == 406
    assert "memory overload" in ch.close_reason.reply_text
    assert broker.metrics.flow_publishes_refused == 1
    assert not c.closed  # channel-level error: the connection survives

    # an independent consumer still drains under refusal (that drain is
    # exactly what de-escalates a real overload)
    con = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    cch = await con.channel()
    for i in range(3):
        m = await cch.basic_get("rf_q", no_ack=True)
        assert m is not None and m.body == b"pre%d" % i
    assert len(queue.messages) == 0

    broker.flow.add("chaos", -125_000)
    assert broker.flow.stage == STAGE_NORMAL
    ch2 = await c.channel()
    ch2.basic_publish(b"recovered", routing_key="rf_q")
    await wait_for(lambda: len(queue.messages) == 1)
    m = await cch.basic_get("rf_q", no_ack=True)
    assert m.body == b"recovered"

    await c.close()
    await con.close()
    await srv.stop()


# ---------------------------------------------------------------------------
# paging, prefetch-size, slow consumers
# ---------------------------------------------------------------------------

async def test_stage1_pages_bodies_to_pressure_cap():
    """Stage 1 shrinks the per-queue resident cap to flow.page-resident:
    the sweep pages queued bodies out (transient included) and gets reap
    hydrate them back intact once pressure clears."""
    broker, srv = await start_broker(
        queue_max_resident=8, flow_page_resident=2,
        message_sweep_interval_s=0.05, flow_high_watermark=64 * 1024)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("pg_q")
    n = 30
    bodies = [b"%05d" % i + b"x" * 1019 for i in range(n)]
    for body in bodies:
        ch.basic_publish(body, routing_key="pg_q")  # transient
    queue = broker.vhosts["/"].queues["pg_q"]
    await wait_for(lambda: len(queue.messages) == n)
    resident_before = broker.resident_bytes
    assert resident_before <= 9 * 1024  # base cap already pages past 8

    # 45000 sits between enter[1]=39321 and enter[2]=65536: page stage
    # only — no throttle, the publisher is untouched
    broker.flow.add("chaos", 45_000)
    assert broker.flow.stage == STAGE_PAGE
    assert broker.flow_paging and not broker.blocked
    await wait_for(lambda: broker.metrics.flow_paged_bodies > 0)
    await wait_for(lambda: broker.resident_bytes <= 4 * 1024)
    assert broker.metrics.flow_paged_bytes > 0

    broker.flow.add("chaos", -45_000)
    assert not broker.flow_paging
    for body in bodies:  # paged bodies hydrate back, in order, intact
        m = await ch.basic_get("pg_q", no_ack=True)
        assert m is not None and m.body == body
    await c.close()
    await srv.stop()


async def test_prefetch_size_budget_enforced():
    """Satellite (a): basic.qos prefetch_size is a BYTE budget — with a
    2500-byte window and 2048-byte bodies, manual-ack delivery goes one
    message at a time; an oversized body still goes through when nothing
    is unacked (RabbitMQ's let-one-through rule)."""
    broker, srv = await start_broker()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("ps_q")
    body = b"q" * 2048
    for _ in range(3):
        ch.basic_publish(body, routing_key="ps_q")
    queue = broker.vhosts["/"].queues["ps_q"]
    await wait_for(lambda: len(queue.messages) == 3)

    await ch.basic_qos(prefetch_size=2500)
    received = []
    await ch.basic_consume("ps_q", received.append, no_ack=False)
    await wait_for(lambda: len(received) == 1)
    await asyncio.sleep(0.2)  # a second delivery would breach the budget
    assert len(received) == 1
    ch.basic_ack(received[0].delivery_tag)
    await wait_for(lambda: len(received) == 2)
    await asyncio.sleep(0.1)
    assert len(received) == 2
    ch.basic_ack(received[1].delivery_tag)
    await wait_for(lambda: len(received) == 3)
    ch.basic_ack(received[2].delivery_tag)

    # oversized single message: delivered as long as nothing is unacked
    ch.basic_publish(b"B" * 3000, routing_key="ps_q")
    await wait_for(lambda: len(received) == 4)
    assert received[3].body == b"B" * 3000
    ch.basic_ack(received[3].delivery_tag)
    await c.close()
    await srv.stop()


async def test_slow_consumer_buffer_detection_and_reset():
    """chana.mq.flow.consumer-buffer: a consumer whose rendered-but-unsent
    delivery bytes exceed the bound stops taking (detected once per
    episode); the detection clears when the connection's output buffer
    drains to the kernel, and delivery continues."""
    broker, srv = await start_broker(flow_consumer_buffer=4096)
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("sl_q")
    received = []
    await ch.basic_consume("sl_q", received.append, no_ack=True)
    queue = broker.vhosts["/"].queues["sl_q"]
    await wait_for(lambda: len(queue.consumers) == 1)
    consumer = queue.consumers[0]

    ch.basic_publish(b"d" * 512, routing_key="sl_q")
    await wait_for(lambda: len(received) == 1)

    # drive the admission check at a deterministic buffer level instead of
    # racing the writer loop's drain
    consumer.buffered_bytes = 5000
    assert consumer.can_take(100) is False
    assert consumer.slow is True
    assert broker.metrics.flow_slow_consumers == 1
    assert consumer.can_take(100) is False  # one detection per episode
    assert broker.metrics.flow_slow_consumers == 1

    # kernel drain resets the episode and re-opens admission
    consumer.channel.connection._reset_consumer_buffers()
    assert consumer.buffered_bytes == 0 and consumer.slow is False
    assert consumer.can_take(100) is True

    for i in range(5):  # end-to-end: delivery still flows after the episode
        ch.basic_publish(b"post%d" % i, routing_key="sl_q")
    await wait_for(lambda: len(received) == 6)
    assert [m.body for m in received[1:]] == [b"post%d" % i for i in range(5)]
    await c.close()
    await srv.stop()


# ---------------------------------------------------------------------------
# readiness coupling
# ---------------------------------------------------------------------------

async def test_health_surfaces_stage_not_ready_only_at_refuse():
    """Satellite (b): /admin/health always surfaces the ladder stage, but
    readiness only drops at refuse — a throttling broker is still doing
    useful work and must keep its traffic."""
    from chanamq_tpu.rest.admin import AdminServer
    from chanamq_tpu.telemetry import TelemetryService
    from chanamq_tpu.telemetry.alerts import default_rules

    broker, srv = await start_broker(flow_high_watermark=64 * 1024)
    broker.telemetry = TelemetryService(
        broker, interval_s=1.0, ring_ticks=16, rules=default_rules())
    admin = AdminServer(broker, host="127.0.0.1", port=0)
    await admin.start()

    async def http_health():
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(b"GET /admin/health HTTP/1.1\r\n\r\n")
        raw = await asyncio.wait_for(reader.read(-1), 10)
        writer.close()
        return raw.split(b"\r\n", 1)[0]

    out = broker.telemetry.health()
    assert out["ready"] is True
    mp = out["checks"]["memory_pressure"]
    assert mp["ok"] is True and mp["stage_label"] == "normal"
    assert (await http_health()).startswith(b"HTTP/1.1 200")

    broker.flow.add("chaos", 80 * 1024)  # throttle: degraded but READY
    out = broker.telemetry.health()
    assert out["ready"] is True
    assert out["checks"]["memory_pressure"]["stage_label"] == "throttle"

    broker.flow.add("chaos", 45_000)  # 125000 total: refuse -> NOT ready
    out = broker.telemetry.health()
    assert out["ready"] is False
    assert any("memory pressure" in r for r in out["reasons"])
    assert (await http_health()).startswith(b"HTTP/1.1 503")

    broker.flow.add("chaos", -125_000)
    assert broker.telemetry.health()["ready"] is True
    assert (await http_health()).startswith(b"HTTP/1.1 200")

    await admin.stop()
    await srv.stop()


async def test_health_fallback_without_telemetry_sees_pressure():
    """Telemetry is off by default — the /admin/health fallback must still
    surface the ladder and go 503 at refuse, or a default-config broker
    under overload keeps taking load-balanced traffic."""
    from chanamq_tpu.rest.admin import AdminServer

    broker, srv = await start_broker(flow_high_watermark=64 * 1024)
    assert getattr(broker, "telemetry", None) is None
    admin = AdminServer(broker, host="127.0.0.1", port=0)
    await admin.start()

    async def http_health():
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", admin.bound_port)
        writer.write(b"GET /admin/health HTTP/1.1\r\n\r\n")
        raw = await asyncio.wait_for(reader.read(-1), 10)
        writer.close()
        import json
        return (raw.split(b"\r\n", 1)[0],
                json.loads(raw.split(b"\r\n\r\n", 1)[1]))

    status, out = await http_health()
    assert status.startswith(b"HTTP/1.1 200")
    assert out["checks"]["memory_pressure"]["stage_label"] == "normal"

    broker.flow.add("chaos", 125_000)  # refuse
    status, out = await http_health()
    assert status.startswith(b"HTTP/1.1 503")
    assert out["ready"] is False
    assert any("memory pressure" in r for r in out["reasons"])

    broker.flow.add("chaos", -125_000)
    status, _ = await http_health()
    assert status.startswith(b"HTTP/1.1 200")
    await admin.stop()
    await srv.stop()


# ---------------------------------------------------------------------------
# scripted scenarios
# ---------------------------------------------------------------------------

async def test_overload_soak_invariants():
    """The ISSUE acceptance scenario end-to-end: scripted memory-pressure
    chaos pushes the broker to refuse; accounted bytes stay under the hard
    limit, nothing confirmed is lost, paging + refusals + the exact
    memory-pressure alert all happen, and the broker returns to normal
    with a full channel.flow resume."""
    report = await asyncio.wait_for(run_overload_soak(7, messages=96), 120)
    assert report["violations"] == []
    assert report["under_hard_limit"] is True
    assert report["publishes_refused"] > 0
    assert report["paged_bodies"] > 0
    assert report["drained_under_refuse"] > 0
    assert report["confirmed"] == report["delivered_unique"] == 96
    assert report["duplicates"] == 0
    assert tuple(report["alerts"]["fired_rules"]) == OVERLOAD_ALERT_RULES
    assert report["final_stage"] == 0
    assert report["flow_resumes"] >= 1


async def test_connection_churn_leaks_nothing():
    """Satellite (f): connect/declare/publish/disconnect cycles — half of
    them abrupt transport aborts — leave zero accounted bytes behind."""
    report = await asyncio.wait_for(run_connection_churn(cycles=60), 120)
    assert report["violations"] == []
    assert report["leaked_bytes"] == 0
    assert report["aborted"] == 30
    assert report["final_stage"] == 0
    assert report["live_queues"] == 0
