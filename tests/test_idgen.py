"""Snowflake id generator tests (reference: IdGenerator.scala:13-92)."""

import threading

import pytest

from chanamq_tpu.cluster.idgen import IdGenerator, MAX_WORKER_ID


def test_monotonic_unique():
    gen = IdGenerator(worker_id=1)
    ids = gen.next_ids(10_000)
    assert ids == sorted(ids)
    assert len(set(ids)) == len(ids)


def test_worker_id_embedded():
    gen = IdGenerator(worker_id=42)
    assert (gen.next_id() >> 12) & 0x3FF == 42


def test_timestamp_extraction():
    import time

    gen = IdGenerator(worker_id=0)
    before = int(time.time() * 1000)
    ts = IdGenerator.timestamp_ms(gen.next_id())
    after = int(time.time() * 1000)
    assert before <= ts <= after


def test_worker_id_bounds():
    with pytest.raises(ValueError):
        IdGenerator(worker_id=MAX_WORKER_ID + 1)
    with pytest.raises(ValueError):
        IdGenerator(worker_id=-1)


def test_thread_safety():
    gen = IdGenerator(worker_id=3)
    all_ids = []
    lock = threading.Lock()

    def worker():
        ids = [gen.next_id() for _ in range(2000)]
        with lock:
            all_ids.extend(ids)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(all_ids)) == len(all_ids)
