"""Broker-native event bus + firehose tap (RabbitMQ's ``amq.rabbitmq.event``
exchange and firehose tracer, recast onto this broker's own machinery).

Every internal transition the subsystems already log — alert fire/resolve,
control decisions, lifecycle states, flow-ladder stages, chaos fault fires,
profiler slow-callback episodes, connection and queue lifecycle, shard
respawns — is additionally published as an ordinary AMQP message on the
per-vhost system topic exchange ``amq.chanamq.event``, with a structured
routing key (``alert.fired.<rule>``, ``flow.stage.<n>``, ...) and a JSON
body carrying the same payload the log line carries. Any plain AMQP client
binds a queue and consumes: the broker dogfoods its own routing, dispatch
and QoS for its own observability.

The firehose (``chana.mq.firehose.*``) is the per-message sibling: it taps
publishes and deliveries into ``amq.chanamq.trace`` with routing keys
``publish.<exchange>`` / ``deliver.<queue>``, and is gated on the flow
accountant's stage so a slow firehose consumer sheds taps instead of
building unbounded memory (tapped copies are accounted bytes like any
queue resident, so backlog pressure raises the stage, which stops taps).

Gating discipline is identical to chaos/trace/profile: module-level
``ACTIVE`` / ``FIREHOSE`` are ``None`` unless enabled, and every emit seam
costs one attribute load plus an identity check when off. With the bus on
but nothing bound, an emit is one topic-trie walk that returns empty — the
event is dropped O(1), no message object is ever built.
"""

from __future__ import annotations

from typing import Optional

from .bus import EventBus, Firehose, EVENT_EXCHANGE, TRACE_EXCHANGE  # noqa: F401

ACTIVE: Optional[EventBus] = None
FIREHOSE: Optional[Firehose] = None


def install(bus: Optional[EventBus],
            firehose: Optional[Firehose] = None) -> None:
    global ACTIVE, FIREHOSE
    ACTIVE = bus
    if firehose is not None or bus is None:
        FIREHOSE = firehose


def clear() -> None:
    global ACTIVE, FIREHOSE
    ACTIVE = None
    FIREHOSE = None


def enable_from_config(config, broker):
    """Boot-time wiring (``chana.mq.events.enabled`` /
    ``chana.mq.firehose.enabled``): build the bus and/or firehose from the
    knobs, hang the bus off the broker for introspection, install the
    module gates."""
    bus = None
    firehose = None
    if config.bool("chana.mq.events.enabled"):
        bus = EventBus(
            broker,
            vhost=config.str("chana.mq.events.vhost") or "/",
        )
        broker.events = bus
    if config.bool("chana.mq.firehose.enabled"):
        firehose = Firehose(
            broker,
            vhost=config.str("chana.mq.firehose.vhost") or "/",
            queue_filter=config.str("chana.mq.firehose.queue-filter") or "",
            tenant_filter=config.str("chana.mq.firehose.tenant") or "",
        )
    install(bus, firehose)
    return bus, firehose
