"""Message tracing gate — same idiom as :mod:`chanamq_tpu.chaos`.

``ACTIVE`` is the module-level runtime; every hot-path seam costs one
module-attribute load plus an ``is None`` check when tracing is off, so
the disabled broker keeps PR 3's numbers.  Enable via config::

    chana.mq.trace.enabled = true
    chana.mq.trace.sample-rate = 0.01
    chana.mq.trace.ring-size = 256
    chana.mq.trace.slow-ms = 250

or install a :class:`TraceRuntime` directly (tests, bench).
"""

from __future__ import annotations

from typing import Optional

from .runtime import (  # noqa: F401  (package API)
    CLUSTER_PUSH, DELIVER, ENQUEUE, FLOW_THROTTLE, FLUSH_WAIT, INGRESS_PARSE,
    INTRA_SHARD_HOP, REMOTE_APPLY, REPLICATE_SHIP, ROUTE, SETTLE, STAGE_KEYS,
    STAGES, WAL_APPEND, WAL_COMMIT, Trace, TraceRuntime, decode_trailer,
    encode_trailer,
)

ACTIVE: Optional[TraceRuntime] = None


def install(runtime: TraceRuntime) -> TraceRuntime:
    global ACTIVE
    ACTIVE = runtime
    return runtime


def clear() -> None:
    global ACTIVE
    ACTIVE = None


def current_trace_id() -> Optional[str]:
    """Trace id of the publish being processed right now, if sampled."""
    rt = ACTIVE
    if rt is None:
        return None
    cur = rt.current
    return cur.trace_id if cur is not None else None


def current_w3c_trace_id() -> Optional[str]:
    """The propagated W3C trace id of the current publish, if any — the
    join key structured logs share with exported spans and exemplars."""
    rt = ACTIVE
    if rt is None:
        return None
    cur = rt.current
    if cur is None or cur.w3c is None:
        return None
    return cur.w3c.trace_id


def enable_from_config(config, broker) -> Optional[TraceRuntime]:
    """Install tracing per the ``chana.mq.trace.*`` block.

    The sampling seed defaults to the installed chaos plan's seed so a
    seeded soak samples the same messages run over run.
    """
    if not config.bool("chana.mq.trace.enabled"):
        return None
    from .. import chaos  # lazy: avoid import cycle at package load

    if chaos.ACTIVE is not None:
        seed = chaos.ACTIVE.plan.seed
    else:
        seed = config.int("chana.mq.chaos.seed")
    runtime = TraceRuntime(
        sample_rate=float(config.get("chana.mq.trace.sample-rate")),
        ring_size=config.int("chana.mq.trace.ring-size"),
        slow_ms=float(config.get("chana.mq.trace.slow-ms")),
        metrics=broker.metrics,
        seed=seed,
        node=getattr(broker, "trace_node", "local"),
    )
    broker.trace_enabled = True
    return install(runtime)
