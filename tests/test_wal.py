"""WAL storage engine tests: crash recovery (torn tail, CRC corruption,
replay-over-checkpoint idempotence), the confirm-at-commit-boundary
ordering contract, frame codec semantics, key compaction, and tiered
sealed-segment offload/rehydration (chanamq_tpu/wal/)."""

import asyncio
import os
import struct
import threading

import pytest

from chanamq_tpu.store.api import StoredMessage, StoredQueue
from chanamq_tpu.store.sqlite import SqliteStore
from chanamq_tpu.wal import CHECKPOINT_KEY, WalStore
from chanamq_tpu.wal.codec import (
    OP_INDEX, decode_payload, encode_record, scan_frames,
)
from chanamq_tpu.wal.segment import list_segments
from chanamq_tpu.wal.tier import StreamTier, compact_records

pytestmark = pytest.mark.asyncio

_HDR = struct.Struct("<II")


@pytest.fixture
def db_path(tmp_path):
    return str(tmp_path / "store.db")


def make_store(db_path, **kwargs):
    kwargs.setdefault("flush_ms", 1.0)
    # far future by default: tests that want a checkpoint trigger one by
    # hand so the segment lifecycle is deterministic
    kwargs.setdefault("checkpoint_ms", 3_600_000.0)
    return WalStore(SqliteStore(db_path), **kwargs)


def msg(i: int) -> StoredMessage:
    return StoredMessage(id=i, properties_raw=b"\x01", body=b"body%d" % i,
                         exchange="ex", routing_key="rk", refer_count=1)


async def crash(store: WalStore) -> None:
    """Simulated SIGKILL: abandon loops and buffers, no close(), no final
    commit — whatever reached the segment files is all recovery gets."""
    store._commit_task.cancel()
    store._checkpoint_task.cancel()
    store._inner._closed = True
    store._executor.shutdown(wait=True)
    store._inner._executor.shutdown(wait=False)


def wipe_index(db_path: str) -> None:
    """Erase the inner index the way a lost SQLite batch would: recovery
    must rebuild these rows from the WAL alone."""
    import sqlite3
    db = sqlite3.connect(db_path)
    db.execute("DELETE FROM msgs")
    db.commit()
    db.close()


def frame_offsets(path: str) -> list[int]:
    """Byte offset of every frame in a segment file."""
    with open(path, "rb") as f:
        data = f.read()
    offsets, pos = [], 0
    while pos + _HDR.size <= len(data):
        length, _crc = _HDR.unpack_from(data, pos)
        offsets.append(pos)
        pos += _HDR.size + length
    return offsets


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------


async def test_codec_roundtrip_and_scan_states():
    rec = encode_record(7, OP_INDEX["insert_queue_msg"],
                        ("/", "q", 1, 100, 10, None))
    payloads, good, status = scan_frames(rec + rec)
    assert status == "ok" and good == len(rec) * 2
    assert [decode_payload(p)[0] for p in payloads] == [7, 7]
    lsn, op, args = decode_payload(payloads[0])
    assert op == OP_INDEX["insert_queue_msg"]
    assert args == ("/", "q", 1, 100, 10, None)

    # torn: the final frame is cut short -> droppable tail
    payloads, good, status = scan_frames(rec + rec[:-3])
    assert status == "torn" and good == len(rec) and len(payloads) == 1

    # corrupt: a damaged frame with intact data behind it -> stop point
    bad = bytearray(rec + rec)
    bad[_HDR.size + 2] ^= 0xFF
    payloads, good, status = scan_frames(bytes(bad))
    assert status == "corrupt" and payloads == []


async def test_codec_stored_dataclass_values():
    m = msg(3)
    rec = encode_record(1, OP_INDEX["insert_message"], (m,))
    _lsn, _op, (back,) = decode_payload(scan_frames(rec)[0][0])
    assert back == m
    q = StoredQueue(vhost="/", name="q", durable=True,
                    arguments={"x-stream-compact": True})
    rec = encode_record(2, OP_INDEX["insert_queue_meta"], (q,))
    _lsn, _op, (back,) = decode_payload(scan_frames(rec)[0][0])
    assert back == q


# ---------------------------------------------------------------------------
# crash recovery
# ---------------------------------------------------------------------------


async def test_torn_tail_truncated_on_recovery(db_path):
    s = make_store(db_path)
    await s.open()
    lo = s.mark()
    for i in range(10):
        s.insert_message_nowait(msg(i))
    await s.flush([(lo, s.mark())])
    await crash(s)

    # the crash tore the last frame mid-write; the index lost its batch
    segs = list_segments(s.dir)
    assert len(segs) == 1
    with open(segs[0][1], "r+b") as f:
        f.truncate(f.seek(0, os.SEEK_END) - 3)
    wipe_index(db_path)

    s2 = make_store(db_path)
    await s2.open()
    assert s2.recovered_records == 9
    assert s2.metrics.wal_recover_torn == 1
    got = await s2.select_messages(list(range(10)))
    assert sorted(got) == list(range(9))  # the torn record is gone
    await s2.close()


async def test_crc_corruption_stops_replay_and_quarantines(db_path):
    s = make_store(db_path)
    await s.open()
    lo = s.mark()
    for i in range(20):
        s.insert_message_nowait(msg(i))
    await s.flush([(lo, s.mark())])
    await crash(s)

    segs = list_segments(s.dir)
    path = segs[0][1]
    offsets = frame_offsets(path)
    assert len(offsets) == 20
    # flip one payload byte of frame 10: replay must stop THERE — records
    # behind a damaged one are ordered after it, so applying them would
    # reorder history
    with open(path, "r+b") as f:
        f.seek(offsets[10] + _HDR.size + 1)
        byte = f.read(1)
        f.seek(offsets[10] + _HDR.size + 1)
        f.write(bytes([byte[0] ^ 0xFF]))
    wipe_index(db_path)

    s2 = make_store(db_path)
    await s2.open()
    assert s2.recovered_records == 10
    assert s2.metrics.wal_recover_corrupt >= 1
    got = await s2.select_messages(list(range(20)))
    assert sorted(got) == list(range(10))
    # the unreplayable segment is kept aside as evidence, not deleted
    assert any(name.endswith(".corrupt") for name in os.listdir(s2.dir))
    await s2.close()


async def test_replay_over_checkpoint_is_idempotent(db_path):
    s = make_store(db_path, checkpoint_ms=50.0)
    await s.open()
    lo = s.mark()
    for i in range(100):
        s.insert_message_nowait(msg(i))
        s.insert_queue_msg_nowait("/", "q", i + 1, i, 5, None)
    await s.flush([(lo, s.mark())])
    await s.insert_queue_meta(StoredQueue(vhost="/", name="q"))
    wid = await s.allocate_worker_id()
    for _ in range(100):
        await asyncio.sleep(0.05)
        ck = await s._inner.get_kv(CHECKPOINT_KEY)
        if ck is not None and int(ck) >= s.mark():
            break
    assert int(ck) >= s.mark()
    # tail written after the checkpoint: the only part recovery may replay
    lo = s.mark()
    for i in range(100, 150):
        s.insert_message_nowait(msg(i))
    await s.flush([(lo, s.mark())])
    await crash(s)

    s2 = make_store(db_path)
    await s2.open()
    # replay covered exactly the post-checkpoint tail; the checkpointed
    # prefix was NOT re-applied (it no longer exists in any segment)...
    assert s2.recovered_records == 50
    # ...yet replaying over rows the write-through already landed is safe:
    # every journaled op is INSERT OR REPLACE / DELETE shaped
    got = await s2.select_messages(list(range(150)))
    assert len(got) == 150
    for i in range(150):
        assert got[i].body == b"body%d" % i
    q = await s2.select_queue("/", "q")
    assert q is not None and len(q.msgs) == 100
    # the journaled worker-id floor survives the crash: no id reuse
    assert await s2.allocate_worker_id() > wid

    # recovery itself re-checkpointed: a second boot replays only what
    # s2 appended after it (the one journaled worker-id floor), never
    # the 252-record history it already folded into the index
    await crash(s2)
    s3 = make_store(db_path)
    await s3.open()
    assert s3.recovered_records == 1
    await s3.close()
    # clean shutdown checkpoints everything: the WAL dir holds no segments
    assert list_segments(s3.dir) == []


async def test_clean_restart_replays_nothing(db_path):
    s = make_store(db_path)
    await s.open()
    await s.insert_queue_meta(StoredQueue(vhost="/", name="q"))
    await s.close()
    s2 = make_store(db_path)
    await s2.open()
    assert s2.recovered_records == 0
    assert (await s2.select_queue("/", "q")) is not None
    await s2.close()


# ---------------------------------------------------------------------------
# confirm-at-commit-boundary ordering
# ---------------------------------------------------------------------------


async def test_confirm_barrier_waits_for_fsync(db_path):
    """A durability barrier (what releases a publisher confirm) must not
    resolve before the group commit's write+fsync completes — stall the
    writer's sync and the barrier must stall with it."""
    s = make_store(db_path)
    await s.open()
    gate = threading.Event()
    synced = threading.Event()
    orig_sync = s._writer.sync

    def gated_sync(fsync):
        assert gate.wait(10), "test gate never released"
        orig_sync(fsync)
        synced.set()

    s._writer.sync = gated_sync
    lo = s.mark()
    s.insert_message_nowait(msg(1))
    fut = asyncio.ensure_future(s.flush([(lo, s.mark())]))
    await asyncio.sleep(0.2)
    assert not fut.done(), "confirm released before the fsync happened"
    gate.set()
    await asyncio.wait_for(fut, 10)
    assert synced.is_set()
    s._writer.sync = orig_sync
    await s.close()


async def test_failed_commit_raises_only_overlapping_barriers(db_path):
    """Commit-failure attribution: the barrier whose LSN window rode the
    failed batch raises; a later barrier over a healthy batch succeeds."""
    s = make_store(db_path)
    await s.open()
    orig_append = s._writer.append
    fail_once = [True]

    def flaky_append(data, last_lsn):
        if fail_once[0]:
            fail_once[0] = False
            raise OSError("disk on fire")
        orig_append(data, last_lsn)

    s._writer.append = flaky_append
    lo = s.mark()
    s.insert_message_nowait(msg(1))
    with pytest.raises(RuntimeError):
        await s.flush([(lo, s.mark())])
    assert s.metrics.wal_commit_errors == 1
    assert s.error_count >= 1
    lo = s.mark()
    s.insert_message_nowait(msg(2))
    await s.flush([(lo, s.mark())])  # healthy batch: must not raise
    await s.close()


async def test_group_commit_batches_many_appends(db_path):
    """The whole point: hundreds of appends from interleaved 'channels'
    amortize into a handful of fsyncs, not one per op."""
    s = make_store(db_path, flush_ms=5.0)
    await s.open()
    lo = s.mark()
    for i in range(500):
        s.insert_message_nowait(msg(i))
        s.insert_queue_msg_nowait("/", "q", i + 1, i, 5, None)
    await s.flush([(lo, s.mark())])
    # each blob+row pair fuses into one insert_published record
    assert s.metrics.wal_appends == 500
    assert s.metrics.wal_fsyncs <= 3
    await s.close()


async def test_fused_publish_record_recovers_blob_and_row(db_path):
    """insert_message_nowait + insert_queue_msg_nowait for the same id
    journal as ONE insert_published record, and recovery expands it back
    into both index writes."""
    s = make_store(db_path)
    await s.open()
    await s.insert_queue_meta(StoredQueue(vhost="/", name="q"))
    lo = s.mark()
    for i in range(20):
        s.insert_message_nowait(msg(i))
        s.insert_queue_msg_nowait("/", "q", i + 1, i, 5, None)
    await s.flush([(lo, s.mark())])
    assert s.metrics.wal_appends == 21  # queue meta + 20 fused publishes
    await crash(s)
    wipe_index(db_path)

    s2 = make_store(db_path)
    await s2.open()
    got = await s2.select_messages(list(range(20)))
    assert sorted(got) == list(range(20))
    q = await s2.select_queue("/", "q")
    assert q is not None and len(q.msgs) == 20
    await s2.close()


def test_coalesce_splits_half_dead_fused_record():
    """A fused publish whose blob OR row (not both) dies inside the batch
    forwards only the living half to the index."""
    from chanamq_tpu.wal.engine import _coalesce_ops

    pub = ("insert_published", (msg(1), "/", "q", 7, 5, None))
    # blob deleted -> only the queue-log row survives
    net, elided = _coalesce_ops([pub, ("delete_messages", ([1],))])
    assert net == [("insert_queue_msg", ("/", "q", 7, 1, 5, None))]
    # row consumed past the watermark -> only the blob survives
    net, elided = _coalesce_ops(
        [pub, ("update_queue_last_consumed", ("/", "q", 7))])
    assert [n for n, _ in net] == ["insert_message",
                                   "update_queue_last_consumed"]
    # both halves dead -> the record never reaches SQLite
    net, elided = _coalesce_ops(
        [pub, ("update_queue_last_consumed", ("/", "q", 7)),
         ("delete_messages", ([1],))])
    assert [n for n, _ in net] == ["update_queue_last_consumed"]


async def test_error_count_aggregates_inner(db_path):
    s = make_store(db_path)
    await s.open()
    assert s.error_count == 0
    s._inner.error_count += 1  # a lost background index write
    assert s.error_count == 1  # readiness sees one number
    await s.close()


# ---------------------------------------------------------------------------
# key compaction + tiered offload
# ---------------------------------------------------------------------------


def _stream_blob(base: int, keys: list) -> tuple:
    import chanamq_tpu.broker  # noqa: F401  (streams import needs broker first)
    from chanamq_tpu.streams.segment import StreamRecord, pack_records
    records = [
        StreamRecord(base + i, 1000 + i, "ex", key, b"\x01", b"v%d" % i)
        for i, key in enumerate(keys)
    ]
    return records, pack_records(records)


async def test_compact_records_keeps_newest_per_key():
    records, _blob = _stream_blob(1, ["a", "b", "a", "c", "b"])
    seen: set = set()
    kept, dropped = compact_records(records, seen)
    assert dropped == 2
    assert [(r.offset, r.routing_key) for r in kept] == [
        (3, "a"), (4, "c"), (5, "b")]
    # an older segment compacts against the keys this one established
    older, _ = _stream_blob(0, ["c"])
    kept2, dropped2 = compact_records(older, seen)
    assert kept2 == [] and dropped2 == 1


async def test_wal_compacts_declared_stream_queues(db_path):
    from chanamq_tpu.streams.segment import unpack_records
    s = make_store(db_path, compact_streams=True)
    await s.open()
    await s.insert_queue_meta(StoredQueue(
        vhost="/", name="sq", arguments={
            "x-queue-type": "stream", "x-stream-compact": True}))
    # two sealed segments with overlapping keys: k0 repeats in the newer
    _, blob1 = _stream_blob(1, ["k0", "k1", "k2"])
    _, blob2 = _stream_blob(4, ["k0", "k3"])
    await s.insert_stream_segment("/", "sq", 1, 3, 0, 0, len(blob1), blob1)
    await s.insert_stream_segment("/", "sq", 4, 5, 0, 0, len(blob2), blob2)
    await s._maintain_streams()
    assert s.metrics.wal_compactions == 1
    assert s.metrics.wal_compacted_records == 1
    old = await s.select_stream_segment("/", "sq", 1)
    offsets = [r.offset for r in unpack_records(old)]
    assert offsets == [2, 3]  # k0@1 compacted away; newer seg untouched
    new = await s.select_stream_segment("/", "sq", 4)
    assert [r.offset for r in unpack_records(new)] == [4, 5]
    # sparse-safe decode: holes stay addressable by offset
    from chanamq_tpu.streams.segment import unpack_records_indexed
    slots = unpack_records_indexed(old, 1, 3)
    assert slots[0] is None and slots[1].offset == 2
    await s.close()


async def test_tier_offload_and_rehydrate(db_path):
    s = make_store(db_path, tier_keep_segments=1)
    await s.open()
    await s.insert_queue_meta(StoredQueue(
        vhost="/", name="sq", arguments={"x-queue-type": "stream"}))
    _, blob1 = _stream_blob(1, ["a", "b"])
    _, blob2 = _stream_blob(3, ["c", "d"])
    await s.insert_stream_segment("/", "sq", 1, 2, 0, 0, len(blob1), blob1)
    await s.insert_stream_segment("/", "sq", 3, 4, 0, 0, len(blob2), blob2)
    await s._maintain_streams()
    assert s.metrics.wal_tier_offloads == 1
    # the cold blob left SQLite but the index row remains; reads rehydrate
    assert await s._inner.select_stream_segment("/", "sq", 1) is None
    metas = await s.stream_segment_metas("/", "sq")
    assert [m[0] for m in metas] == [1, 3]
    back = await s.select_stream_segment("/", "sq", 1)
    assert back == blob1
    assert s.metrics.wal_tier_rehydrations == 1
    # retention drop cleans the tier file too
    await s.delete_stream_segments("/", "sq", [1])
    assert not s.tier.has("/", "sq", 1)
    assert await s.select_stream_segment("/", "sq", 1) is None
    await s.close()


async def test_tier_file_crc_damage_reads_as_absent(tmp_path):
    tier = StreamTier(str(tmp_path / "tier"))
    tier.write("/", "q", 5, b"payload-bytes")
    assert tier.read("/", "q", 5) == b"payload-bytes"
    path = tier._path("/", "q", 5)
    with open(path, "r+b") as f:
        f.write(b"\xff")
    assert tier.read("/", "q", 5) is None  # damaged, never silent garbage


async def test_broker_restart_hydrates_tiered_segments_on_cursor_read(db_path):
    """Full recovery path for tiered offload: a broker seals stream
    segments, the maintenance pass tiers the cold ones out of SQLite
    (tier_keep_segments=1), the broker restarts on the same data dir, and
    a cursor read from offset "first" must deliver every record — the
    cold blobs hydrate transparently through select_stream_segment."""
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient

    persistent = BasicProperties(delivery_mode=2)
    store = make_store(db_path, tier_keep_segments=1)
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0, store=store)
    await srv.start()
    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await conn.channel()
    await ch.confirm_select()
    await ch.queue_declare("tsq", durable=True, arguments={
        "x-queue-type": "stream", "x-stream-max-segment-size-bytes": 256})
    for i in range(30):
        ch.basic_publish(b"t%03d" % i, routing_key="tsq",
                         properties=persistent)
    await ch.wait_unconfirmed_below(1, timeout=15)
    queue = srv.broker.get_queue("/", "tsq")
    if queue._active:
        queue._seal_active()
    sealed = len(queue._seg_bases)
    assert sealed >= 3, "segment cap too large to exercise tiering"
    for _ in range(250):  # spills ride store_bg: wait for all to land
        if len(await store.stream_segment_metas("/", "tsq")) == sealed:
            break
        await asyncio.sleep(0.02)
    await store._maintain_streams()
    assert store.metrics.wal_tier_offloads >= sealed - 1
    await conn.close()
    await srv.stop()

    store2 = make_store(db_path, tier_keep_segments=1)
    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=store2)
    await srv2.start()
    queue2 = srv2.broker.get_queue("/", "tsq")
    assert queue2.next_offset == 31
    # recovery rebuilds the index cold: metadata only, no resident records
    assert all(seg.records is None for seg in queue2._segments)
    conn2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
    ch2 = await conn2.channel()
    await ch2.basic_qos(prefetch_count=64)
    got: list = []
    done = asyncio.get_event_loop().create_future()

    def on_msg(msg):
        got.append(bytes(msg.body))
        ch2.basic_ack(msg.delivery_tag)
        if len(got) >= 30 and not done.done():
            done.set_result(None)

    tag = await ch2.basic_consume("tsq", on_msg,
                                  arguments={"x-stream-offset": "first"})
    await asyncio.wait_for(done, 15)
    await ch2.basic_cancel(tag)
    assert got == [b"t%03d" % i for i in range(30)]
    assert store2.metrics.wal_tier_rehydrations >= 1
    await conn2.close()
    await srv2.stop()
