"""Stream queues: append-only segmented logs with server-tracked cursors.

See streams/queue.py for semantics; selected per queue with
``x-queue-type: stream`` at declare time.
"""

from .groups import (  # noqa: F401
    GROUP_CURSOR_PREFIX,
    GROUP_MODES,
    StreamGroup,
    validate_group_args,
)
from .queue import (  # noqa: F401
    GET_CURSOR,
    VALID_QUEUE_TYPES,
    StreamCursor,
    StreamQueue,
    parse_offset_spec,
)
from .segment import (  # noqa: F401
    Segment,
    StreamRecord,
    pack_records,
    unpack_records,
)
