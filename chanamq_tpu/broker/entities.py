"""Broker entities: Message, Queue, Exchange, VHost.

Capability parity with the reference's entity actors:
- Message         <- MessageEntity (entity/MessageEntity.scala:33-200):
                     body held once, reference-counted per routed queue,
                     deleted (and removed from store) at refcount 0.
- Queue           <- QueueEntity (entity/QueueEntity.scala:34-488): ordered
                     offsets, TTL clamp min(msg, queue), unacked bookkeeping,
                     consumer registry with auto-delete, exclusive ownership,
                     lastConsumed watermark persistence.
- Exchange        <- ExchangeEntity (entity/ExchangeEntity.scala:66-410):
                     typed matcher, durable-persistence decision, auto-delete
                     on last unbind.
- VHost           <- VhostEntity (entity/VhostEntity.scala:20-131) plus the
                     per-vhost entity registries.

Architectural difference, by design: the reference delivers by *polling*
every out-active channel on a 1 microsecond tick (ServerBluePrint.scala:31-38,
FrameStage.scala:366-453). Here each queue owns an event-driven dispatch
step — enqueue/ack/consume/qos/flow events schedule one coalesced dispatch
pass on the event loop (call_soon), which round-robins eligible consumers.
No polling, no idle CPU burn, and delivery latency is one loop hop.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from collections import deque
from typing import TYPE_CHECKING, Any, Callable, Optional

from .. import profile, trace
from ..amqp.properties import BasicProperties
from ..semantics.priority import PriorityFan
from ..store.api import StoredMessage
from .matchers import Matcher, matcher_for

if TYPE_CHECKING:  # pragma: no cover
    from .broker import Broker
    from .channel import Consumer, ServerChannel


log = logging.getLogger("chanamq.broker")


def now_ms() -> int:
    return int(time.time() * 1000)


class Message:
    """A message body + properties, shared (refcounted) across queues."""

    __slots__ = (
        "id", "properties", "body", "exchange", "routing_key",
        "ttl_ms", "refer_count", "persisted", "published_ns", "header_raw",
        "accounted", "paged", "exrk_raw", "trace",
    )

    def __init__(
        self,
        id: int,
        properties: BasicProperties,
        body: bytes,
        exchange: str,
        routing_key: str,
        ttl_ms: Optional[int] = None,
        header_raw: Optional[bytes] = None,
    ) -> None:
        self.id = id
        self.properties = properties
        self.body = body
        self.exchange = exchange
        self.routing_key = routing_key
        self.ttl_ms = ttl_ms
        self.refer_count = 0
        self.persisted = False
        self.published_ns = time.perf_counter_ns()
        # wire-format content-header payload; rendered lazily when absent
        # and reused for every delivery + the persisted blob
        self.header_raw = header_raw
        # body bytes counted in Broker.resident_bytes (cleared on
        # passivation / final unrefer so accounting never double-releases)
        self.accounted = False
        # blob written to the store ONLY for passivation (transient message
        # paged out under memory pressure) — deleted at refcount 0 like a
        # persisted blob, but never promised durable: no queue-log/unack
        # rows are written for it and recovery never resurrects it
        self.paged = False
        # length-prefixed exchange + routing-key wire slice (as basic.deliver
        # frames need it); captured from the publish frame when available,
        # else built lazily by the first deliver render
        self.exrk_raw: Optional[bytes] = None
        # sampled trace riding this message (chanamq_tpu/trace/); attached
        # by push_local / the data-plane handlers only when sampled
        self.trace = None

    def header_payload(self) -> bytes:
        hp = self.header_raw
        if hp is None:
            hp = self.properties.encode_header(len(self.body))
            self.header_raw = hp
        return hp

    @property
    def is_persistent(self) -> bool:
        return self.properties.delivery_mode == 2


class QueuedMessage:
    """A message's residency in one queue (offset, expiry, redelivery mark).

    body_size is recorded separately from the message so QoS accounting and
    store bookkeeping keep working while the body itself is passivated
    (paged out to the store, reference: MessageEntity.scala:168-198)."""

    __slots__ = ("message", "offset", "expire_at_ms", "redelivered",
                 "body_size", "dead", "priority")

    def __init__(
        self, message: Message, offset: int, expire_at_ms: Optional[int],
        body_size: Optional[int] = None,
    ) -> None:
        self.message = message
        self.offset = offset
        self.expire_at_ms = expire_at_ms
        self.redelivered = False
        self.body_size = len(message.body) if body_size is None else body_size
        # set when hydration finds the stored blob gone (TTL'd / deleted):
        # dispatch and pop discard dead entries
        self.dead = False
        # effective message priority (priority queues only; 0 elsewhere)
        self.priority = 0

    def is_expired(self, now: Optional[int] = None) -> bool:
        return self.expire_at_ms is not None and (now or now_ms()) >= self.expire_at_ms


class Delivery:
    """An unacked delivery: the link channel<->queue for one message."""

    __slots__ = ("queued", "queue", "channel", "consumer_tag", "delivery_tag",
                 "no_ack", "delivered_at_ms")

    def __init__(
        self,
        queued: QueuedMessage,
        queue: "Queue",
        channel: "ServerChannel",
        consumer_tag: str,
        delivery_tag: int,
        no_ack: bool,
    ) -> None:
        self.queued = queued
        self.queue = queue
        self.channel = channel
        self.consumer_tag = consumer_tag
        self.delivery_tag = delivery_tag
        self.no_ack = no_ack
        # ack-timeout clock (chana.mq.consumer.timeout; RabbitMQ's
        # consumer_timeout): a delivery unacked past the deadline closes
        # its channel so a stuck consumer can't pin messages forever
        self.delivered_at_ms = now_ms()


class Queue:
    """One message queue within a vhost."""

    # queue-type discriminant: StreamQueue (streams/queue.py) overrides to
    # True; broker paths that differ by type branch on this, not isinstance
    is_stream = False

    HYDRATE_BATCH = 128
    # resident head kept in RAM for x-queue-mode=lazy queues: exactly one
    # dispatch hydration batch, so the consumer never stalls on an empty
    # resident head (defined in terms of HYDRATE_BATCH to keep the
    # invariant under tuning)
    LAZY_RESIDENT = HYDRATE_BATCH

    def __init__(
        self,
        broker: "Broker",
        vhost: str,
        name: str,
        *,
        durable: bool = False,
        exclusive_owner: Optional[int] = None,
        auto_delete: bool = False,
        ttl_ms: Optional[int] = None,
        arguments: Optional[dict[str, Any]] = None,
    ) -> None:
        self.broker = broker
        self.vhost = vhost
        self.name = name
        self.durable = durable
        self.exclusive_owner = exclusive_owner  # connection id or None
        self.auto_delete = auto_delete
        self.ttl_ms = ttl_ms
        self.arguments = arguments or {}
        # queue-argument extensions beyond the reference (which supports
        # only x-message-ttl, QueueEntity.scala:288-297): dead-letter
        # routing, ready-backlog length/byte caps (drop-head overflow), and
        # idle auto-expiry — RabbitMQ-compatible argument names/semantics
        args = self.arguments
        self.dlx: Optional[str] = args.get("x-dead-letter-exchange")
        self.dlx_rk: Optional[str] = args.get("x-dead-letter-routing-key")
        self.max_length: Optional[int] = args.get("x-max-length")
        self.max_length_bytes: Optional[int] = args.get("x-max-length-bytes")
        self.expires_ms: Optional[int] = args.get("x-expires")
        # x-queue-mode=lazy (RabbitMQ lazy queues): page bodies out beyond
        # a small resident head instead of the broker-wide watermark —
        # maps straight onto the passivation machinery
        self.max_resident_override: Optional[int] = (
            self.LAZY_RESIDENT if args.get("x-queue-mode") == "lazy" else None)
        # x-max-priority (RabbitMQ priority queues): ready messages order by
        # (priority desc, offset) instead of plain FIFO. Because consumption
        # then leaves offset order, the lastConsumed watermark cannot prune
        # the durable queue log — settles delete their rows individually
        # (coalesced per tick) and recovery replays whatever rows remain.
        self.max_priority: Optional[int] = args.get("x-max-priority")
        self._row_del_buf: list[int] = []
        # x-single-active-consumer (RabbitMQ SAC): deliveries go only to
        # the longest-registered consumer; when it cancels or dies the
        # next registrant takes over automatically
        self.single_active = bool(args.get("x-single-active-consumer"))
        self.last_used = now_ms()
        # body bytes across READY messages (limit enforcement + gauge)
        self.ready_bytes = 0
        # monotonic per-queue counters: the telemetry sampler derives
        # per-queue publish/deliver/ack rates from their deltas
        self.n_published = 0
        self.n_delivered = 0
        self.n_acked = 0
        # whether this queue is reflected in the broker-wide entity gauges
        # (queue_depth/queue_unacked/queue_consumers); gauges_detach()
        # clears it at deletion so late settles cannot double-subtract
        self._counted = True
        # replication log when this node owns a replicated queue (bound by
        # ReplicationManager.attach); every durable store mutation below
        # mirrors itself into it so followers track exactly the rows a
        # restart of THIS node would recover
        self.repl = None  # Optional[replicate.QueueRepLog]

        # ready list: plain FIFO deque, or — when x-max-priority is set —
        # the per-priority fan (semantics/priority.py), which keeps the
        # same (priority desc, offset) iteration order with O(1) enqueue
        # and dispatch instead of ordered-insert scans
        self.messages: Any = (
            deque() if self.max_priority is None
            else PriorityFan(self.max_priority))
        self.next_offset = 1
        self.last_consumed = 0
        self.consumers: list["Consumer"] = []
        self._rr_index = 0
        # priority dispatch groups ([consumers, rotation-index] per level,
        # highest first); None = all default priority (flat RR fast path)
        self._prio_groups: Optional[list[list]] = None
        self.outstanding: dict[int, Delivery] = {}  # msg offset -> delivery
        self.had_consumer = False  # auto-delete arms only after first consumer
        self.deleted = False
        self._dispatch_scheduled = False
        # per-tick store-write coalescing (hot delivery/ack paths)
        self._wm_dirty = False  # a watermark persist is scheduled
        self._unack_del_buf: list[int] = []
        # passivation: an async head-hydration pass is in flight
        self._hydrating = False
        self._hydrate_task: Optional[asyncio.Task] = None
        # entries this queue paged out, in offset order, so a hydration
        # pass is O(batch) instead of rescanning the resident prefix of
        # self.messages; entries hydrated/dropped by other paths are
        # lazily skipped. A fanout sibling can page a shared body without
        # touching this deque — _hydrate_head falls back to a full scan
        # when the passivated head isn't covered.
        self._passivated: deque[QueuedMessage] = deque()

    # -- introspection ----------------------------------------------------

    def touch(self) -> None:
        """Mark the queue used (x-expires idle clock reset)."""
        self.last_used = now_ms()

    @property
    def message_count(self) -> int:
        self._expire_head()
        return len(self.messages)

    @property
    def consumer_count(self) -> int:
        return len(self.consumers)

    def has_exclusive_consumer(self) -> bool:
        return any(c.exclusive for c in self.consumers)

    # -- enqueue ----------------------------------------------------------

    def clamp_expiry(self, message: Message) -> Optional[int]:
        """Effective expiry = now + min(per-message TTL, queue x-message-ttl)
        (reference: QueueEntity.scala:288-297). Allocation-free: runs once
        per enqueued message."""
        mt = message.ttl_ms
        qt = self.ttl_ms
        if mt is None:
            if qt is None:
                return None
            ttl = qt
        elif qt is None or mt < qt:
            ttl = mt
        else:
            ttl = qt
        return now_ms() + ttl

    def push(self, message: Message, body_size: Optional[int] = None) -> QueuedMessage:
        # body_size is computed ONCE by the publisher and passed to every
        # routed queue: a fanout sibling may already have passivated the
        # shared body (message.body is None), so it can't be re-measured here
        qm = QueuedMessage(message, self.next_offset, self.clamp_expiry(message),
                           body_size=body_size)
        self.next_offset += 1
        if self.max_priority is None:
            self.messages.append(qm)
        else:
            # ceiling clamp (RabbitMQ: priority above x-max-priority is
            # treated as the maximum, not an error)
            qm.priority = min(message.properties.priority or 0,
                              self.max_priority)
            self.messages.append(qm)  # fan routes by qm.priority
            self.broker.metrics.semantics_priority_msgs += 1
        self.ready_bytes += qm.body_size
        self.n_published += 1
        if self._counted:
            self.broker.queue_depth += 1
        if self.durable and message.persisted:
            self.broker.store.insert_queue_msg_nowait(
                self.vhost, self.name, qm.offset, message.id,
                qm.body_size, qm.expire_at_ms,
            )
            if self.repl is not None:
                # before this call's own passivation below, so the body is
                # normally still resident; a fanout sibling may already have
                # paged it (body None) — the follower then resyncs the blob
                if trace.ACTIVE is not None and message.trace is not None:
                    t_repl = time.perf_counter_ns()
                    self.repl.enqueue(qm, message)
                    message.trace.span(
                        trace.REPLICATE_SHIP, t_repl,
                        time.perf_counter_ns(), self.broker.trace_node)
                else:
                    self.repl.enqueue(qm, message)
        # length/byte caps: drop-head overflow, dead-lettering each victim
        # (x-overflow=drop-head is the only supported policy; declare
        # rejects others). Runs before passivation so a dropped entry is
        # never paged out.
        if self.max_length is not None or self.max_length_bytes is not None:
            if self._drop_overflow(watch=qm):
                # the pushed entry itself overflowed (tiny cap): it is
                # settled, so skip passivation and just wake dispatch
                self.schedule_dispatch()
                return qm
        # deep-backlog passivation (reference: MessageEntity pages ANY
        # inactive body out — transient included — persisting it first,
        # MessageEntity.scala:171-186): beyond the per-queue resident
        # watermark, drop the body from RAM. Persistent bodies are already
        # in the store (the blob insert was enqueued at publish and rides
        # the same FIFO store queue, so hydration reads always see it);
        # transient bodies are written now, flagged paged-not-persisted so
        # no durability promise attaches and recovery never resurrects
        # them. Dispatch hydrates either kind back on demand.
        max_resident = (self.max_resident_override
                        if self.max_resident_override is not None
                        else self.broker.queue_max_resident)
        # flow stage >= 1 tightens the cap to the pressure watermark, but
        # only where passivation is enabled at all: a 0 cap is an explicit
        # operator opt-out that memory pressure must not override
        page_cap = self.broker.flow_page_resident_active
        if max_resident and page_cap and page_cap < max_resident:
            max_resident = page_cap
        if (max_resident and len(self.messages) > max_resident
                and message.body is not None):
            if not (message.persisted or message.paged):
                message.paged = True
                self.broker.store.insert_message_nowait(
                    StoredMessage(
                        id=message.id,
                        properties_raw=message.header_payload(),
                        body=message.body, exchange=message.exchange,
                        routing_key=message.routing_key,
                        refer_count=message.refer_count,
                        ttl_ms=message.ttl_ms,
                    ))
            if message.accounted:
                self.broker.account_memory(-len(message.body))
                message.accounted = False
            # only the body pages out; properties/header_raw stay so a
            # hydrated delivery needs just the blob read
            message.body = None
            self._passivated.append(qm)
            if page_cap:
                self.broker.metrics.flow_paged_bodies += 1
                self.broker.metrics.flow_paged_bytes += qm.body_size
        self.schedule_dispatch()
        return qm

    def passivate_excess(self, cap: int) -> int:
        """Stage-1 pressure actuation (Broker._sweep_loop): page every
        resident body past the pressure cap out to the store, oldest part
        of the tail first — the head stays resident so dispatch serves it
        without a hydration round-trip. Same per-entry mechanics as the
        push-path passivation above; respects a queue whose passivation
        is explicitly disabled (cap 0)."""
        if self.is_stream or cap <= 0:
            return 0
        base = (self.max_resident_override
                if self.max_resident_override is not None
                else self.broker.queue_max_resident)
        if not base:
            return 0
        cap = min(cap, base)
        if len(self.messages) <= cap:
            return 0
        broker = self.broker
        paged = 0
        for qm in itertools.islice(self.messages, cap, None):
            message = qm.message
            if message.body is None:
                continue
            if not (message.persisted or message.paged):
                message.paged = True
                broker.store.insert_message_nowait(
                    StoredMessage(
                        id=message.id,
                        properties_raw=message.header_payload(),
                        body=message.body, exchange=message.exchange,
                        routing_key=message.routing_key,
                        refer_count=message.refer_count,
                        ttl_ms=message.ttl_ms,
                    ))
            if message.accounted:
                broker.account_memory(-len(message.body))
                message.accounted = False
            message.body = None
            self._passivated.append(qm)
            paged += 1
            broker.metrics.flow_paged_bodies += 1
            broker.metrics.flow_paged_bytes += qm.body_size
        return paged

    def _requeue_priority(self, qm: QueuedMessage) -> None:
        """Requeue into (priority desc, offset asc) position. Durable
        bookkeeping: the dispatch that delivered this entry buffered a
        delete of its queue-log row — if that delete has NOT flushed yet,
        cancel it (the row is still there) instead of re-inserting behind
        it, which would let the flush erase the re-inserted row."""
        self.messages.requeue(qm)  # offset-ordered within its band
        if self.durable and qm.message.persisted:
            try:
                self._row_del_buf.remove(qm.offset)
                row_present = True
            except ValueError:
                row_present = False
            self.broker.store_bg(
                self.broker.store.delete_queue_unacks(
                    self.vhost, self.name, [qm.message.id]))
            if not row_present:
                self.broker.store_bg(
                    self.broker.store.insert_queue_msg(
                        self.vhost, self.name, qm.offset, qm.message.id,
                        qm.body_size, qm.expire_at_ms))
            if self.repl is not None:
                # row_add strictly before unack_del: the unack entry holds
                # the follower's last blob reference until the row re-lands
                if not row_present:
                    self.repl.append("row_add", {
                        "o": qm.offset, "m": qm.message.id,
                        "z": qm.body_size, "e": qm.expire_at_ms})
                self.repl.append("unack_del", {"ids": [qm.message.id]})

    def _drop_overflow(self, watch: Optional[QueuedMessage] = None) -> bool:
        """Enforce x-max-length / x-max-length-bytes by dropping from the
        head (oldest first), dead-lettering each victim (RabbitMQ
        drop-head semantics: the cap bounds READY messages). Returns True
        if `watch` (the just-pushed entry) was among the victims — identity
        is tracked explicitly because a priority insert may land anywhere,
        not just at the tail."""
        messages = self.messages
        dropped_watch = False
        while messages and (
            (self.max_length is not None and len(messages) > self.max_length)
            or (self.max_length_bytes is not None
                and self.ready_bytes > self.max_length_bytes)
        ):
            qm = messages.popleft()
            if qm is watch:
                dropped_watch = True
            self.ready_bytes -= qm.body_size
            if self._counted:
                self.broker.queue_depth -= 1
            self._advance_watermark(qm)
            self._settle_dead(qm, "maxlen")
        if self._passivated:
            self._prune_passivated()
        return dropped_watch

    def _settle_dead(self, qm: QueuedMessage, reason: str) -> None:
        """A message died in this queue (expired / rejected / overflowed):
        forward to the dead-letter exchange when configured, else release
        the reference. `is not None` matters: DLX "" (the default exchange,
        routing straight to a queue named by x-dead-letter-routing-key) is
        a legal RabbitMQ pattern."""
        if self.dlx is not None and not qm.dead:
            # settled from this queue's perspective: hydration and
            # passivated-deque pruning must skip it even while the async
            # dead-letter publish still holds the message reference
            qm.dead = True
            self.broker.dead_letter(self, qm, reason)
        else:
            self.broker.unrefer(qm.message)

    # -- dequeue / dispatch ------------------------------------------------

    def _expire_head(self) -> None:
        """Drop expired and dead (blob gone from the store) head entries."""
        now = now_ms()
        expired = False
        while self.messages and (
                self.messages[0].dead or self.messages[0].is_expired(now)):
            qm = self.messages.popleft()
            self.ready_bytes -= qm.body_size
            if self._counted:
                self.broker.queue_depth -= 1
            self._advance_watermark(qm)
            self._settle_dead(qm, "expired")
            expired = True
        if expired and self._passivated:
            # settled (expired) entries must leave the passivated deque too:
            # on a consumerless TTL'd queue nothing else ever prunes it, and
            # each retained entry pins a Message (properties + header_raw)
            # invisibly to the resident_bytes gauge
            self._prune_passivated()


    def _advance_watermark(self, qm: QueuedMessage) -> None:
        if self.max_priority is not None:
            # priority queues consume out of offset order: the watermark
            # cannot prune, so each settled entry deletes its own row
            # (coalesced into one executemany per loop tick)
            if self.durable and qm.message.persisted and not self.deleted:
                buf = self._row_del_buf
                buf.append(qm.offset)
                if len(buf) == 1:
                    asyncio.get_event_loop().call_soon(self._flush_row_deletes)
            return
        if qm.offset > self.last_consumed:
            self.last_consumed = qm.offset
            if self.durable and not self._wm_dirty:
                # coalesce: one persisted watermark write per loop tick, with
                # the value re-read at flush time (covers every advance and
                # any requeue rewind in between)
                self._wm_dirty = True
                asyncio.get_event_loop().call_soon(self._persist_watermark)

    def _flush_row_deletes(self) -> None:
        offsets, self._row_del_buf = self._row_del_buf, []
        if offsets and not self.deleted:
            self.broker.store_bg(
                self.broker.store.delete_queue_msgs_offsets(
                    self.vhost, self.name, offsets))
            if self.repl is not None:
                self.repl.append("row_del", {"offs": offsets})

    def _persist_watermark(self) -> None:
        self._wm_dirty = False
        if self.deleted:
            return
        self.broker.store_bg(
            self.broker.store.update_queue_last_consumed(
                self.vhost, self.name, self.last_consumed
            )
        )
        if self.repl is not None:
            self.repl.append("watermark", {"wm": self.last_consumed})

    def flush_store_buffers(self) -> None:
        """Flush per-tick coalescing buffers now (shutdown path)."""
        if self._wm_dirty:
            self._persist_watermark()
        self._flush_unack_deletes()
        if self._row_del_buf:
            self._flush_row_deletes()

    def schedule_dispatch(self) -> None:
        if self._dispatch_scheduled or self.deleted:
            return
        if not self.messages or not self.consumers:
            return
        self._dispatch_scheduled = True
        asyncio.get_event_loop().call_soon(self._dispatch)

    def _dispatch(self) -> None:
        """One coalesced dispatch pass: round-robin messages to eligible
        consumers until either runs out (reference's fair poll,
        AMQChannel.scala:43-48 + FrameStage.scala:380-443, turned inside out
        into an event-driven push)."""
        self._dispatch_scheduled = False
        if self.deleted:
            return
        # dispatch-pass ledger window: two stamps per coalesced pass, not
        # per delivery. The pass is ~all delivery rendering, so the same
        # window feeds both the top-level "dispatch" stage (calls=passes,
        # thread-CPU so the attribution busy-sum stays steal-proof) and
        # the fine "deliver" stage (calls=messages, so ns/calls reads
        # as us per delivered message). The pass is synchronous, so no
        # other ledger window can interleave inside it.
        prof = profile.ACTIVE
        t_pass = 0
        n_before = 0
        if prof is not None:
            t_pass = time.thread_time_ns()
            n_before = self.n_delivered
        new_unacks: list[tuple[int, int, int, Optional[int]]] = []
        messages = self.messages
        while messages and self.consumers:
            # expiry is checked on the head inline (no clock read for the
            # overwhelming TTL-less case); head checks and the pop below
            # all act on the same entry, so no re-validation is needed
            qm = messages[0]
            if qm.dead or (qm.expire_at_ms is not None
                           and qm.expire_at_ms <= now_ms()):
                self._expire_head()
                if not messages:
                    break
                qm = messages[0]
            if qm.message.body is None:
                # head is passivated: reattach bodies from the store first;
                # dispatch resumes when the hydration pass completes
                # (reference: MessageEntity.Get lazy store load,
                # MessageEntity.scala:82-102)
                self._start_hydration()
                break
            consumer = self._next_eligible_consumer(qm.body_size)
            if consumer is None:
                break
            messages.popleft()
            self.ready_bytes -= qm.body_size
            if self._counted:
                self.broker.queue_depth -= 1
            delivery = consumer.deliver(self, qm)
            self._advance_watermark(qm)
            self.n_delivered += 1
            if delivery is None:  # no_ack: consumed immediately
                self.broker.unrefer(qm.message)
            else:
                self.outstanding[qm.offset] = delivery
                if self._counted:
                    self.broker.queue_unacked += 1
                if self.durable and qm.message.persisted:
                    new_unacks.append(
                        (qm.message.id, qm.offset, qm.body_size, qm.expire_at_ms)
                    )
        if new_unacks:
            self.broker.store.insert_queue_unacks_nowait(
                self.vhost, self.name, new_unacks)
            if self.repl is not None:
                self.repl.append(
                    "unacks", {"rows": [list(r) for r in new_unacks]})
        # native batch egress: render every connection's buffered delivery
        # records now, INSIDE the dispatch ledger window, so the encode
        # cost stays attributed to dispatch/deliver (the per-connection
        # call_soon guard only catches deliveries buffered outside a
        # dispatch pass — streams, cluster stubs)
        dirty = self.broker.egress_dirty
        if dirty:
            for conn in list(dirty):
                conn.flush_egress()
            dirty.clear()
        if prof is not None:
            dt = time.thread_time_ns() - t_pass
            sns, sc = prof.stage_ns, prof.stage_calls
            sns[profile.DISPATCH] += dt
            sc[profile.DISPATCH] += 1
            delivered = self.n_delivered - n_before
            if delivered:
                sns[profile.DELIVER] += dt
                sc[profile.DELIVER] += delivered

    # -- passivation / hydration -------------------------------------------

    def _start_hydration(self) -> None:
        if self._hydrating or self.deleted:
            return
        self._hydrating = True
        self._hydrate_task = asyncio.get_event_loop().create_task(
            self._hydrate_head())

    def _prune_passivated(self) -> None:
        """Drop settled entries (hydrated / dead / final-unreferred) off the
        front of the passivated deque. basic_get hydrates bodies without
        going through _collect_hydrate_targets — without this prune a
        publish-burst → basic_get-drain cycle would retain every hydrated
        body through the deque forever, invisible to resident_bytes."""
        passivated = self._passivated
        while passivated:
            qm = passivated[0]
            if (qm.dead or qm.message.refer_count <= 0
                    or qm.message.body is not None):
                passivated.popleft()
            else:
                break

    def _collect_hydrate_targets(self) -> list[QueuedMessage]:
        """Pop the next hydration batch off the passivated deque, lazily
        discarding entries already settled by other paths (hydrated via
        basic_get, dead, purged/final-unreferred)."""
        targets: list[QueuedMessage] = []
        while self._passivated and len(targets) < self.HYDRATE_BATCH:
            qm = self._passivated[0]
            if qm.dead or qm.message.refer_count <= 0:
                self._passivated.popleft()
                continue
            if qm.message.body is not None:
                self._passivated.popleft()
                continue
            targets.append(self._passivated.popleft())
        return targets

    async def _hydrate_head(self) -> None:
        """Batch-reattach passivated bodies at the queue head from the store.
        Entries whose blob is gone (TTL'd / deleted) are marked dead and
        discarded by the next _expire_head pass."""
        failed = False
        targets: list[QueuedMessage] = []
        try:
            targets = self._collect_hydrate_targets()
            head = self.messages[0] if self.messages else None
            if (head is not None and head.message.body is None
                    and not head.dead
                    and (not targets or targets[0] is not head)):
                # the passivated head isn't covered by our own deque: a
                # fanout sibling paged the shared body out from under us
                # (entities.py push nulls message.body for every routed
                # queue). Full scan of the resident prefix — rare path.
                self._passivated.extendleft(reversed(targets))
                targets = []
                for qm in self.messages:
                    if len(targets) >= self.HYDRATE_BATCH:
                        break
                    if qm.message.body is None and not qm.dead:
                        targets.append(qm)
            if not targets:
                return
            stored = await self.broker.store.select_messages(
                [qm.message.id for qm in targets])
            if self.deleted:
                return
            for qm in targets:
                msg = qm.message
                if qm.dead or msg.refer_count <= 0:
                    # purged/expired while the read was in flight: its final
                    # unrefer already ran, so reattaching would leak the
                    # resident_bytes accounting forever
                    continue
                sm = stored.get(msg.id)
                if sm is None:
                    qm.dead = True
                elif msg.body is None:
                    msg.body = sm.body
                    if msg.header_raw is None:
                        msg.header_raw = sm.properties_raw
                    self.broker.account_memory(len(sm.body))
                    msg.accounted = True
        except Exception:
            failed = True
            # return unfinished targets so the retry pass finds them again
            # (duplicates vs fallback-scanned entries are lazily skipped
            # once hydrated)
            self._passivated.extendleft(reversed(targets))
            log.exception("hydration of queue %s failed; retrying in 1s",
                          self.name)
        finally:
            self._hydrating = False
            self._hydrate_task = None
        if failed:
            # store trouble: back off instead of dispatch->hydrate spinning
            asyncio.get_event_loop().call_later(1.0, self.schedule_dispatch)
        else:
            self.schedule_dispatch()

    def _next_eligible_consumer(self, size: int) -> Optional["Consumer"]:
        """Round-robin pick of a consumer with prefetch budget for a
        `size`-byte delivery (reference fair poll: AMQChannel.scala:43-48).
        With x-priority consumers present (RabbitMQ extension), higher
        priorities are served first while they have budget, round-robin
        within a level; the flat fast path is untouched otherwise."""
        if self.single_active:
            # SAC: one active consumer — the highest x-priority, earliest-
            # registered within that level (RabbitMQ 3.12+ activates by
            # priority); plain SAC queues use pure registration order
            if not self.consumers:
                return None
            if self._prio_groups is not None:
                consumer = self._prio_groups[0][0][0]
            else:
                consumer = self.consumers[0]
            return consumer if consumer.can_take(size) else None
        if self._prio_groups is not None:
            return self._next_by_priority(size)
        n = len(self.consumers)
        for i in range(n):
            consumer = self.consumers[(self._rr_index + i) % n]
            if consumer.can_take(size):
                self._rr_index = (self._rr_index + i + 1) % n
                return consumer
        return None

    def _next_by_priority(self, size: int) -> Optional["Consumer"]:
        """Walk priority levels high to low; round-robin WITHIN a level via
        its own rotation index (a shared index would let the top level
        reset rotation and starve lower-level siblings). The groups are
        rebuilt only on consumer add/remove, not per delivery."""
        for group in self._prio_groups:
            consumers, start = group[0], group[1]
            n = len(consumers)
            for i in range(n):
                consumer = consumers[(start + i) % n]
                if consumer.can_take(size):
                    group[1] = (start + i + 1) % n
                    return consumer
        return None

    def _rebuild_prio_groups(self) -> None:
        """Consumer set changed: rebuild the priority-ordered dispatch
        groups, or drop back to the flat fast path when every consumer is
        at default priority."""
        if not any(getattr(c, "priority", 0) for c in self.consumers):
            self._prio_groups = None
            return
        levels: dict[int, list] = {}
        for consumer in self.consumers:
            levels.setdefault(getattr(consumer, "priority", 0), []).append(
                consumer)
        self._prio_groups = [
            [levels[priority], 0] for priority in sorted(levels, reverse=True)
        ]

    # -- get (polling read) ------------------------------------------------

    async def basic_get(self) -> Optional[QueuedMessage]:
        """Pop one message, hydrating a passivated head from the store
        first (the reference Promise-latches Get on the lazy store load,
        MessageEntity.scala:82-102). The entry is CLAIMED (popped) before
        the store read so a concurrent dispatch pass can't starve the get."""
        self.last_used = now_ms()
        self._prune_passivated()
        while True:
            self._expire_head()
            if not self.messages:
                return None
            qm = self.messages.popleft()
            self.ready_bytes -= qm.body_size
            if self._counted:
                self.broker.queue_depth -= 1
            msg = qm.message
            if msg.body is None:
                try:
                    stored = await self.broker.store.select_messages([msg.id])
                except Exception:
                    self.messages.appendleft(qm)
                    self.ready_bytes += qm.body_size
                    if self._counted:
                        self.broker.queue_depth += 1
                    raise
                sm = stored.get(msg.id)
                if sm is None:  # blob gone: drop and try the next entry
                    self._advance_watermark(qm)
                    self.broker.unrefer(msg)
                    continue
                if msg.body is None:
                    msg.body = sm.body
                    if msg.header_raw is None:
                        msg.header_raw = sm.properties_raw
                    self.broker.account_memory(len(sm.body))
                    msg.accounted = True
                self._prune_passivated()  # this entry is settled now
            self._advance_watermark(qm)
            self.n_delivered += 1
            return qm

    # -- ack / requeue -----------------------------------------------------

    def note_outstanding(self, delivery: Delivery) -> None:
        """Register an out-of-dispatch delivery (basic.get) as unacked.
        Streams key this differently (cursor, offset), so callers go
        through this hook instead of writing the dict directly."""
        self.outstanding[delivery.queued.offset] = delivery
        if self._counted:
            self.broker.queue_unacked += 1

    def _settle_store(self, delivery: Delivery) -> None:
        popped = self.outstanding.pop(delivery.queued.offset, None)
        if popped is not None and self._counted:
            self.broker.queue_unacked -= 1
        if self.durable and delivery.queued.message.persisted:
            buf = self._unack_del_buf
            buf.append(delivery.queued.message.id)
            if len(buf) == 1:
                asyncio.get_event_loop().call_soon(self._flush_unack_deletes)

    def ack(self, delivery: Delivery) -> None:
        prof = profile.ACTIVE
        t_settle = time.perf_counter_ns() if prof is not None else 0
        self._settle_store(delivery)
        self.n_acked += 1
        if trace.ACTIVE is not None:
            tr = delivery.queued.message.trace
            if tr is not None:
                trace.ACTIVE.on_settle(tr, self.broker.trace_node)
        self.broker.unrefer(delivery.queued.message)
        if prof is not None:
            prof.stage_ns[profile.SETTLE] += (
                time.perf_counter_ns() - t_settle)
            prof.stage_calls[profile.SETTLE] += 1

    def _flush_unack_deletes(self) -> None:
        ids, self._unack_del_buf = self._unack_del_buf, []
        if ids and not self.deleted:
            self.broker.store_bg(
                self.broker.store.delete_queue_unacks(self.vhost, self.name, ids)
            )
            if self.repl is not None:
                self.repl.append("unack_del", {"ids": ids})

    def drop(self, delivery: Delivery) -> None:
        """Reject without requeue: same store cleanup as ack, then the
        message dead-letters (reason "rejected") when a DLX is set."""
        prof = profile.ACTIVE
        t_settle = time.perf_counter_ns() if prof is not None else 0
        self._settle_store(delivery)
        if trace.ACTIVE is not None:
            tr = delivery.queued.message.trace
            if tr is not None:
                trace.ACTIVE.on_settle(tr, self.broker.trace_node)
        self._settle_dead(delivery.queued, "rejected")
        if prof is not None:
            prof.stage_ns[profile.SETTLE] += (
                time.perf_counter_ns() - t_settle)
            prof.stage_calls[profile.SETTLE] += 1

    def requeue(self, delivery: Delivery) -> None:
        """Return an unacked message to the queue, in offset order, marked
        redelivered (reference: QueueEntity.scala:415-446)."""
        popped = self.outstanding.pop(delivery.queued.offset, None)
        if popped is not None and self._counted:
            self.broker.queue_unacked -= 1
        qm = delivery.queued
        qm.redelivered = True
        if qm.is_expired():
            if self.durable and qm.message.persisted:
                self.broker.store_bg(
                    self.broker.store.delete_queue_unacks(
                        self.vhost, self.name, [qm.message.id]
                    )
                )
                if self.repl is not None:
                    self.repl.append("unack_del", {"ids": [qm.message.id]})
            self._settle_dead(qm, "expired")
            return
        self.ready_bytes += qm.body_size
        if self._counted:
            self.broker.queue_depth += 1
        if self.max_priority is not None:
            # priority queues: back into the (priority desc, offset) order;
            # durably, the dispatch deleted this entry's row, so settle the
            # unack row and re-insert the queue-log row (FIFO store thread
            # keeps the pair ordered)
            self._requeue_priority(qm)
            self.schedule_dispatch()
            return
        # insert keeping offset order. Requeues nearly always precede the
        # whole backlog (they were at the head when delivered), so the O(1)
        # end checks cover the hot cases; the linear scan is the rare
        # interleaved-offset fallback.
        if not self.messages or qm.offset < self.messages[0].offset:
            self.messages.appendleft(qm)
        elif qm.offset > self.messages[-1].offset:
            self.messages.append(qm)
        else:
            idx = 0
            for idx, existing in enumerate(self.messages):
                if existing.offset > qm.offset:
                    break
            else:
                idx = len(self.messages)
            self.messages.insert(idx, qm)
        # rewind the watermark so recovery replays it (reference rewinds
        # lastConsumed on requeue)
        if qm.offset <= self.last_consumed:
            self.last_consumed = qm.offset - 1
            if self.durable and qm.message.persisted:
                self.broker.store_bg(
                    self.broker.store.delete_queue_unacks(
                        self.vhost, self.name, [qm.message.id]
                    )
                )
                self.broker.store_bg(
                    self.broker.store.insert_queue_msg(
                        self.vhost, self.name, qm.offset, qm.message.id,
                        qm.body_size, qm.expire_at_ms,
                    )
                )
                self.broker.store_bg(
                    self.broker.store.update_queue_last_consumed(
                        self.vhost, self.name, self.last_consumed
                    )
                )
                if self.repl is not None:
                    # row back first (keeps the blob referenced), then the
                    # unack settle, then the rewound watermark
                    self.repl.append("row_add", {
                        "o": qm.offset, "m": qm.message.id,
                        "z": qm.body_size, "e": qm.expire_at_ms})
                    self.repl.append("unack_del", {"ids": [qm.message.id]})
                    self.repl.append(
                        "watermark", {"wm": self.last_consumed})
        self.schedule_dispatch()

    # -- purge / consumers -------------------------------------------------

    def purge(self) -> int:
        self._expire_head()
        count = len(self.messages)
        for qm in self.messages:
            self._advance_watermark(qm)
            self.broker.unrefer(qm.message)
        if self._counted:
            self.broker.queue_depth -= len(self.messages)
        self.messages.clear()
        self.ready_bytes = 0
        self._passivated.clear()
        # purge_queue_msgs below supersedes any per-row deletes buffered by
        # _advance_watermark for the purged entries (priority queues)
        self._row_del_buf.clear()
        if self.durable:
            self.broker.store_bg(
                self.broker.store.purge_queue_msgs(self.vhost, self.name)
            )
            if self.repl is not None:
                self.repl.append("purge", {})
        return count

    def add_consumer(self, consumer: "Consumer") -> None:
        self.consumers.append(consumer)
        if self._counted:
            self.broker.queue_consumers += 1
        if self._prio_groups is not None or getattr(consumer, "priority", 0):
            self._rebuild_prio_groups()
        self.had_consumer = True
        self.last_used = now_ms()
        self.schedule_dispatch()

    def remove_consumer(self, consumer: "Consumer") -> bool:
        """Returns True if the queue auto-deleted as a result
        (reference: QueueEntity.scala:236-269)."""
        try:
            self.consumers.remove(consumer)
        except ValueError:
            return False
        if self._counted:
            self.broker.queue_consumers -= 1
        if self._prio_groups is not None:
            self._rebuild_prio_groups()
        if self.single_active and self.consumers:
            # SAC succession: the next-longest-registered consumer takes
            # over the backlog immediately
            self.schedule_dispatch()
        self.last_used = now_ms()
        if self.auto_delete and self.had_consumer and not self.consumers:
            return True
        return False

    def gauges_detach(self) -> None:
        """Remove this queue's contribution from the broker-wide entity
        gauges (queue/vhost deletion paths tear down messages/consumers
        directly, bypassing the incremental sites above). Idempotent: a
        settle arriving after deletion must not double-subtract."""
        if not self._counted:
            return
        self._counted = False
        broker = self.broker
        broker.queue_depth -= len(self.messages)
        broker.queue_unacked -= len(self.outstanding)
        broker.queue_consumers -= len(self.consumers)


class Exchange:
    """One exchange within a vhost."""

    def __init__(
        self,
        vhost: str,
        name: str,
        type: str,
        *,
        durable: bool = False,
        auto_delete: bool = False,
        internal: bool = False,
        arguments: Optional[dict[str, Any]] = None,
    ) -> None:
        self.vhost = vhost
        self.name = name
        self.type = type
        self.durable = durable
        self.auto_delete = auto_delete
        self.internal = internal
        self.arguments = arguments or {}
        self.matcher: Matcher = matcher_for(type)
        # exchange-to-exchange bindings (EXCEEDS the reference, which stubs
        # Exchange.Bind/Unbind with TODO logs, FrameStage.scala:1023-1027):
        # a second matcher whose "queue" targets are destination exchange
        # names. None until the first e2e bind, so the common single-hop
        # publish path pays nothing for the feature.
        self.ex_matcher: Optional[Matcher] = None
        # alternate exchange (RabbitMQ extension): messages this exchange
        # cannot route (no binding matched) fall through to the named
        # exchange instead of being dropped/returned
        alt = self.arguments.get("alternate-exchange")
        self.alternate: Optional[str] = alt if isinstance(alt, str) else None

    def ensure_ex_matcher(self) -> Matcher:
        if self.ex_matcher is None:
            self.ex_matcher = matcher_for(self.type)
        return self.ex_matcher

    def route(self, routing_key: str, headers: Optional[dict] = None) -> set[str]:
        return self.matcher.route(routing_key, headers)

    def is_unused(self) -> bool:
        return self.matcher.is_empty() and (
            self.ex_matcher is None or self.ex_matcher.is_empty())

    def equivalent(self, type: str, durable: bool, auto_delete: bool, internal: bool) -> bool:
        return (
            self.type == type.lower()
            and self.durable == durable
            and self.auto_delete == auto_delete
            and self.internal == internal
        )


class VHost:
    """A virtual host: independent namespace of exchanges and queues."""

    # Exchanges every vhost predeclares. The default "" direct exchange binds
    # every queue by its name (AMQP 0-9-1 mandated); amq.* are the standard
    # predeclared set.
    PREDECLARED: tuple[tuple[str, str], ...] = (
        ("", "direct"),
        ("amq.direct", "direct"),
        ("amq.fanout", "fanout"),
        ("amq.topic", "topic"),
        ("amq.headers", "headers"),
        ("amq.match", "headers"),
        # system exchanges (chanamq_tpu/events/): internal events and the
        # firehose tap publish here; clients may bind/consume but the
        # amq.* name guard keeps them undeclarable and undeletable
        ("amq.chanamq.event", "topic"),
        ("amq.chanamq.trace", "topic"),
    )

    def __init__(self, name: str) -> None:
        self.name = name
        self.active = True
        self.exchanges: dict[str, Exchange] = {}
        self.queues: dict[str, Queue] = {}
        for ex_name, ex_type in self.PREDECLARED:
            self.exchanges[ex_name] = Exchange(
                name, ex_name, ex_type, durable=True
            )

    def route(
        self, exchange_name: str, routing_key: str,
        headers: Optional[dict] = None,
        queue_exists: Optional[Callable[[str], bool]] = None,
    ) -> Optional[set[str]]:
        """Resolve target queue names; None when the exchange doesn't exist.

        With exchange-to-exchange bindings present, routing is a cycle-safe
        breadth-first walk of the exchange graph (RabbitMQ semantics: each
        hop re-matches the message's ORIGINAL routing key / headers against
        the next exchange's bindings; queues reached via multiple paths
        receive one copy). Exchanges without e2e bindings take the original
        single-hop path untouched."""
        exchange = self.exchanges.get(exchange_name)
        if exchange is None:
            return None
        if exchange_name == "":
            # default exchange: implicit binding queue-name == routing-key
            return {routing_key} if routing_key in self.queues else set()
        if exchange.ex_matcher is None and exchange.alternate is None:
            return exchange.route(routing_key, headers)
        # graph walk covering e2e bindings AND alternate-exchange fallback:
        # an exchange that routes the key nowhere (no queue, no e2e target)
        # hands it to its alternate; cycle-safe via the visited set
        queues: set[str] = set()
        visited: set[str] = set()
        frontier = {exchange_name}
        while frontier:
            hop: set[str] = set()
            for ex_name in frontier:
                if ex_name in visited:
                    continue
                visited.add(ex_name)
                ex = self.exchanges.get(ex_name)
                if ex is None:
                    continue  # dangling bind to a deleted exchange
                if ex.name == "":
                    # default exchange as an alternate target: implicit
                    # queue-name binding. queue_exists (broker-supplied in
                    # cluster mode) also covers remotely-owned queues that
                    # exist here only as replicated metadata.
                    if routing_key in self.queues or (
                            queue_exists is not None
                            and queue_exists(routing_key)):
                        queues.add(routing_key)
                    continue
                matched = ex.route(routing_key, headers)
                targets = (ex.ex_matcher.route(routing_key, headers)
                           if ex.ex_matcher is not None else set())
                if not matched and not targets and ex.alternate is not None:
                    hop.add(ex.alternate)
                queues |= matched
                hop |= targets
            frontier = hop
        return queues

    def drop_exchange_refs(self, name: str) -> None:
        """An exchange was deleted: remove every e2e binding that targets
        it (RabbitMQ deletes bindings on either side of a dead exchange)."""
        for exchange in self.exchanges.values():
            if exchange.ex_matcher is not None:
                exchange.ex_matcher.unbind_queue(name)
