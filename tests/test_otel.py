"""End-to-end causal tracing (chanamq_tpu/otel/): W3C traceparent
parsing + propagation, forced sampling vs the seeded RNG, blob-v2
compatibility, OTLP span rendering + the background exporter, pull-mode
/admin/otel/spans, /admin/traces filtering, OpenMetrics exemplars, the
cross-cluster joined span tree over a federation link, and the JSON log
join key."""

import asyncio
import json
import re

import pytest

from chanamq_tpu import trace
from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.otel.context import (
    W3CContext, derive_trace_id, extract, format_traceparent,
    parse_traceparent, stamp_headers,
)
from chanamq_tpu.otel.export import (
    OtelExporter, default_resource, resource_spans, span_count,
)
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.trace import (
    DELIVER, ENQUEUE, REMOTE_APPLY, SETTLE, Trace, TraceRuntime,
)
from chanamq_tpu.utils.metrics import Metrics

from test_federation import (
    PERSISTENT, STREAM_SMALL, collect, eventually, start_pair, stop_pair,
)
from test_trace import _http

pytestmark = pytest.mark.asyncio

TID = "0af7651916cd43dd8448eb211c80319c"
SPAN = "b7ad6b7169203331"
TRACEPARENT = f"00-{TID}-{SPAN}-01"


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    trace.clear()


# ---------------------------------------------------------------------------
# traceparent parsing
# ---------------------------------------------------------------------------


async def test_traceparent_parse_table():
    ok = parse_traceparent(TRACEPARENT)
    assert ok == (TID, SPAN, 0x01)
    # bytes arrive from raw AMQP header decode paths
    assert parse_traceparent(TRACEPARENT.encode()) == ok
    # a future version may append fields; version 00 may not
    assert parse_traceparent(f"01-{TID}-{SPAN}-01-extra") == (TID, SPAN, 1)
    for bad in (
        None, "", "garbage", 42,
        f"ff-{TID}-{SPAN}-01",            # version ff is forbidden
        f"00-{'0' * 32}-{SPAN}-01",       # all-zero trace id
        f"00-{TID}-{'0' * 16}-01",        # all-zero span id
        f"00-{TID[:30]}-{SPAN}-01",       # short trace id
        f"00-{TID}-{SPAN[:14]}-01",       # short span id
        f"00-{TID.upper()}-{SPAN}-01",    # uppercase hex is invalid
        f"00-{'zz' * 16}-{SPAN}-01",      # non-hex
        f"00-{TID}-{SPAN}-01-extra",      # version 00 with extra field
        f"0x-{TID}-{SPAN}-01",
    ):
        assert parse_traceparent(bad) is None, bad


async def test_extract_and_format_roundtrip():
    got = extract({"traceparent": TRACEPARENT, "tracestate": "k=v"})
    assert got == (TID, SPAN, 0x01, "k=v")
    assert extract({"traceparent": "junk"}) is None
    assert extract({}) is None and extract(None) is None
    assert format_traceparent(TID, SPAN, 0x01) == TRACEPARENT
    # derived ids are stable and never the forbidden all-zero value
    assert derive_trace_id("n#1") == derive_trace_id("n#1")
    assert derive_trace_id("n#1") != derive_trace_id("n#2")
    assert int(derive_trace_id("n#1"), 16) != 0


async def test_stamp_headers_copy_on_write():
    ctx = W3CContext(TID, SPAN, "c0c0c0c0c0c0c0c0", flags=1)
    props = BasicProperties(headers={"traceparent": TRACEPARENT, "k": "v"})
    out, changed = stamp_headers(props, ctx)
    assert changed and out is not props
    # the cached/shared original is never mutated (connection.py shares
    # decoded BasicProperties across identical header bytes)
    assert props.headers["traceparent"] == TRACEPARENT
    assert out.headers["traceparent"] == ctx.outgoing
    assert out.headers["k"] == "v"
    # idempotent: an already-stamped property set passes through
    again, changed2 = stamp_headers(out, ctx)
    assert not changed2 and again is out


# ---------------------------------------------------------------------------
# forced sampling vs the seeded RNG
# ---------------------------------------------------------------------------


async def test_forced_samples_never_perturb_seeded_sequence():
    """The determinism gate: a headerless run and a run interleaved with
    propagated publishes must make draw-for-draw identical sampling
    decisions (forced traces use a separate counter + derived ids)."""
    rt1 = TraceRuntime(sample_rate=0.5, seed=42)
    plain = [rt1.begin_publish() is not None for _ in range(100)]
    rt2 = TraceRuntime(sample_rate=0.5, seed=42, metrics=Metrics())
    headers = {"traceparent": TRACEPARENT}
    mixed = []
    for i in range(100):
        if i % 3 == 0:
            forced = rt2.begin_publish(headers=headers)
            assert forced is not None and forced.w3c is not None
            assert forced.w3c.trace_id == TID
            assert forced.w3c.parent_span_id == SPAN
            assert forced.w3c.flags & 0x01
        mixed.append(rt2.begin_publish() is not None)
    assert mixed == plain
    assert rt2.metrics.otel_forced_samples == 34
    # malformed headers fall through to the seeded path untouched
    rt3 = TraceRuntime(sample_rate=0.5, seed=42)
    bad = {"traceparent": "not-a-context"}
    assert [rt3.begin_publish(headers=bad) is not None
            for _ in range(100)] == plain


async def test_distinct_root_spans_per_forced_publish():
    rt = TraceRuntime(sample_rate=0.0, seed=1)
    a = rt.begin_publish(headers={"traceparent": TRACEPARENT})
    b = rt.begin_publish(headers={"traceparent": TRACEPARENT})
    assert a.w3c.root_span_id != b.w3c.root_span_id
    assert a.w3c.trace_id == b.w3c.trace_id == TID


# ---------------------------------------------------------------------------
# blob v2
# ---------------------------------------------------------------------------


async def test_blob_v2_roundtrip_and_v1_compat():
    rt = TraceRuntime(sample_rate=0.0)
    tr = rt.begin_publish(headers={
        "traceparent": TRACEPARENT, "tracestate": "vendor=1"})
    tr.attr("exchange", "ex")
    tr.attr("queue", "q1,q2")
    back = Trace.from_blob(tr.to_blob())
    assert back.w3c is not None
    assert back.w3c.trace_id == TID
    assert back.w3c.parent_span_id == SPAN
    assert back.w3c.root_span_id == tr.w3c.root_span_id
    assert back.w3c.tracestate == "vendor=1"
    assert back.attrs == {"exchange": "ex", "queue": "q1,q2"}
    # a seeded (no-w3c, no-attrs) trace roundtrips too
    plain = Trace("n#7", "n")
    got = Trace.from_blob(plain.to_blob())
    assert got.w3c is None and not got.attrs
    # a hand-built v1 blob (pre-ISSUE-20 wire) still decodes: version
    # byte 0x01, ss id, ss origin, zero spans, zero chaos tags
    v1 = b"\x01" + bytes((3,)) + b"n#1" + bytes((1,)) + b"n" \
        + b"\x00" + b"\x00"
    old = Trace.from_blob(v1)
    assert old.trace_id == "n#1" and old.origin == "n"
    assert old.w3c is None and not old.attrs


# ---------------------------------------------------------------------------
# single-broker propagation: publish in, delivery out
# ---------------------------------------------------------------------------


async def _deliver_roundtrip(publish_headers):
    """Publish one message through a live broker with tracing installed
    (seeded rate 0: only a propagated context can sample) and return
    (delivered message, runtime)."""
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
    try:
        client = await AMQPClient.connect("127.0.0.1", server.bound_port)
        ch = await client.channel()
        await ch.queue_declare("oq")
        got = asyncio.get_event_loop().create_future()
        await ch.basic_consume("oq", lambda m: got.done()
                               or got.set_result(m), no_ack=True)
        ch.basic_publish(b"payload", routing_key="oq",
                         properties=BasicProperties(
                             headers=dict(publish_headers)))
        msg = await asyncio.wait_for(got, 10)
        await client.close()
        return msg, rt
    finally:
        await server.stop()


async def test_propagated_publish_restamps_delivery():
    msg, rt = await _deliver_roundtrip({"traceparent": TRACEPARENT,
                                        "tracestate": "k=v"})
    for _ in range(100):
        if rt.ring:
            break
        await asyncio.sleep(0.02)
    tr = rt.ring[-1]
    assert tr.w3c is not None and tr.w3c.trace_id == TID
    # the delivery carries the BROKER's outgoing context: same trace id,
    # the broker's root span as parent, tracestate passed through
    out = msg.properties.headers["traceparent"]
    assert out == f"00-{TID}-{tr.w3c.root_span_id}-01"
    assert out != TRACEPARENT
    assert msg.properties.headers["tracestate"] == "k=v"
    assert bytes(msg.body) == b"payload"
    # full pipeline captured, attrs stamped for the query layer
    for stage in (ENQUEUE, DELIVER, SETTLE):
        assert tr.slots[stage] is not None
    assert tr.attrs["queue"] == "oq" and tr.attrs["vhost"] == "/"
    assert rt.metrics.otel_forced_samples == 1


async def test_malformed_traceparent_never_breaks_publish():
    msg, rt = await _deliver_roundtrip({"traceparent": "00-bogus",
                                        "other": "kept"})
    assert bytes(msg.body) == b"payload"
    # not sampled (rate 0, context invalid), header passed through as-is
    assert msg.properties.headers["traceparent"] == "00-bogus"
    assert msg.properties.headers["other"] == "kept"
    assert not rt.ring and rt.metrics.otel_forced_samples == 0


# ---------------------------------------------------------------------------
# OTLP render + exporter
# ---------------------------------------------------------------------------


def _finished_forced_trace(rt):
    tr = rt.begin_publish(headers={"traceparent": TRACEPARENT})
    rt.current = None
    rt.finish(tr)
    return tr


async def test_resource_spans_shape():
    rt = TraceRuntime(sample_rate=0.0, metrics=Metrics())
    tr = _finished_forced_trace(rt)
    doc = resource_spans([tr], {"service.name": "chanamq-tpu",
                                "chanamq.node": "n1"})
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert span_count(doc) == len(spans) >= 2
    root = next(s for s in spans if s["name"] == "broker")
    assert root["traceId"] == TID
    assert root["parentSpanId"] == SPAN
    assert root["spanId"] == tr.w3c.root_span_id
    for child in spans:
        if child is root:
            continue
        assert child["parentSpanId"] == root["spanId"]
        assert child["traceId"] == TID
        assert int(child["startTimeUnixNano"]) <= \
            int(child["endTimeUnixNano"])
    # the document is pure JSON (OTLP/HTTP collectors eat it directly)
    json.dumps(doc)
    # a seeded trace exports a parentless root under a derived trace id
    seeded = Trace("n1#9", "n1")
    seeded.span(ENQUEUE, 10, 20, "n1")
    sdoc = resource_spans([seeded], {"service.name": "x"})
    sroot = sdoc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert sroot["traceId"] == derive_trace_id("n1#9")
    assert "parentSpanId" not in sroot


class _StubCollector:
    """Minimal OTLP/HTTP collector: accepts POST /v1/traces, records
    the JSON bodies, answers the configured status."""

    def __init__(self, status=b"200 OK"):
        self.status = status
        self.docs = []
        self.server = None

    async def start(self):
        self.server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0)
        return self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    async def _handle(self, reader, writer):
        try:
            head = await reader.readuntil(b"\r\n\r\n")
            length = int(re.search(
                rb"Content-Length: (\d+)", head).group(1))
            self.docs.append(json.loads(await reader.readexactly(length)))
            writer.write(b"HTTP/1.1 " + self.status
                         + b"\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
        finally:
            writer.close()


async def test_exporter_posts_otlp_batches():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
    collector = _StubCollector()
    port = await collector.start()
    otel = OtelExporter(
        server.broker, endpoint=f"http://127.0.0.1:{port}/v1/traces",
        flush_ms=20, max_batch=8)
    await otel.start()
    try:
        assert rt.export_hook == otel.on_trace  # bound methods: ==, not is
        for _ in range(3):
            _finished_forced_trace(rt)  # finish() fans into the hook
        await eventually(lambda: collector.docs, what="otlp post")
        doc = collector.docs[0]
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert all(s["traceId"] == TID for s in spans)
        res = {a["key"]: a["value"] for a in
               doc["resourceSpans"][0]["resource"]["attributes"]}
        assert res["service.name"] == {"stringValue": "chanamq-tpu"}
        m = server.broker.metrics
        assert m.otel_batches_sent >= 1
        assert m.otel_spans_exported >= 6  # 3 roots + >=1 stage each
        assert m.otel_export_errors == 0
        assert otel.queue_depth() == 0
    finally:
        await otel.stop()
        await collector.stop()
        await server.stop()
    assert rt.export_hook is None  # stop() disarms its own hook


async def test_exporter_requeues_on_collector_failure():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
    # port 1 refuses instantly: every flush fails fast through the
    # ReconnectBackoff and the batch goes back to the head of the queue
    otel = OtelExporter(server.broker,
                        endpoint="http://127.0.0.1:1/v1/traces",
                        flush_ms=20)
    await otel.start()
    try:
        _finished_forced_trace(rt)
        await eventually(
            lambda: server.broker.metrics.otel_export_errors >= 1,
            what="export failure")
        assert otel.queue_depth() == 1  # requeued, not dropped
        assert server.broker.metrics.otel_batches_sent == 0
        assert otel.status()["backoff"]["consecutive_failures"] >= 1
    finally:
        await otel.stop()
        await server.stop()


async def test_exporter_sheds_when_full():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
    otel = OtelExporter(server.broker, queue_size=2)  # collector-less
    await otel.start()
    try:
        for _ in range(5):
            _finished_forced_trace(rt)
        assert otel.queue_depth() == 2
        assert server.broker.metrics.otel_spans_shed == 3
    finally:
        await otel.stop()
        await server.stop()


# ---------------------------------------------------------------------------
# admin surface: pull export, trace query, exemplars
# ---------------------------------------------------------------------------


async def test_admin_otel_spans_pull():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        status, _ = await _http(admin.bound_port, "GET",
                                "/admin/otel/spans")
        assert status == 409  # tracing not installed
        rt = trace.install(TraceRuntime(
            sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
        # no exporter: the rings serve the render
        _finished_forced_trace(rt)
        status, doc = await _http(admin.bound_port, "GET",
                                  "/admin/otel/spans")
        assert status == 200 and span_count(doc) >= 2
        # with the exporter installed the pull drains its queue
        otel = OtelExporter(server.broker)
        await otel.start()
        server.broker.otel = otel
        _finished_forced_trace(rt)
        assert otel.queue_depth() == 1
        status, doc = await _http(admin.bound_port, "GET",
                                  "/admin/otel/spans?limit=10")
        assert status == 200 and span_count(doc) >= 2
        assert otel.queue_depth() == 0
        assert server.broker.metrics.otel_pull_served == 1
        # drained: the next pull returns an empty document
        status, doc = await _http(admin.bound_port, "GET",
                                  "/admin/otel/spans")
        assert status == 200 and span_count(doc) == 0
        await otel.stop()
        server.broker.otel = None
    finally:
        await admin.stop()
        await server.stop()


async def test_admin_traces_filtering_and_otlp():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    rt = trace.install(TraceRuntime(
        sample_rate=1.0, metrics=server.broker.metrics, node="n1"))
    try:
        for i, (ex, q) in enumerate(
                [("orders", "q1"), ("orders", "q2"), ("audit", "q1")]):
            tr = rt.begin_publish()
            tr.attr("exchange", ex)
            tr.attr("queue", f"{q},shared")
            tr.attr("vhost", "/")
            rt.current = None
            rt.finish(tr)
        status, body = await _http(admin.bound_port, "GET",
                                   "/admin/traces?exchange=orders")
        assert status == 200 and body["matched"] == 2
        assert all(t["attrs"]["exchange"] == "orders"
                   for t in body["traces"])
        # queue filter matches any member of the comma-joined fanout set
        status, body = await _http(admin.bound_port, "GET",
                                   "/admin/traces?queue=shared")
        assert status == 200 and body["matched"] == 3
        status, body = await _http(
            admin.bound_port, "GET",
            "/admin/traces?queue=q1&exchange=audit")
        assert status == 200 and body["matched"] == 1
        status, body = await _http(admin.bound_port, "GET",
                                   "/admin/traces?vhost=missing")
        assert status == 200 and body["matched"] == 0
        # min_duration_us alone also selects the filtered view
        status, body = await _http(
            admin.bound_port, "GET",
            "/admin/traces?min_duration_us=999999999")
        assert status == 200 and body["matched"] == 0
        # ?format=otlp renders the matched set as one OTLP document
        status, doc = await _http(
            admin.bound_port, "GET",
            "/admin/traces?exchange=orders&format=otlp")
        assert status == 200 and "resourceSpans" in doc
        assert span_count(doc) >= 2
        # the unfiltered listing keeps its historical shape
        status, body = await _http(admin.bound_port, "GET",
                                   "/admin/traces")
        assert status == 200 and "recent" in body
        assert "stage_latency_us" in body and "traces" not in body
        # bad limit is a 400, not a 500
        status, body = await _http(admin.bound_port, "GET",
                                   "/admin/traces?exchange=x&limit=nope")
        assert status == 400
    finally:
        await admin.stop()
        await server.stop()


_EXEMPLAR_RE = re.compile(
    r'^chanamq_[a-z0-9_]+_bucket\{le="[^"]+"\} \d+ '
    r'# \{trace_id="[0-9a-f]{32}"\} [0-9.]+(e[+-]?\d+)? \d+(\.\d+)?$')


async def _scrape(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    return raw.partition(b"\r\n\r\n")[2].decode()


async def test_openmetrics_exemplars():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=server.broker.metrics, node="n1"))
    try:
        tr = _finished_forced_trace(rt)
        server.broker.metrics.publish_to_deliver_us.observe_us(
            tr.total_us)
        text = await _scrape(admin.bound_port,
                             "/metrics?format=openmetrics")
        lines = text.splitlines()
        assert lines[-1] == "# EOF"
        exemplar_lines = [l for l in lines if " # {" in l]
        assert exemplar_lines, "expected at least one exemplar"
        for line in exemplar_lines:
            assert _EXEMPLAR_RE.match(line), line
        # the propagated W3C trace id is the join key on every family
        # this trace populated
        assert any(f'trace_id="{TID}"' in l for l in exemplar_lines)
        # the plain scrape is untouched: no exemplars, no EOF marker
        plain = await _scrape(admin.bound_port, "/metrics")
        assert " # {" not in plain and "# EOF" not in plain
        # exemplar-covered families are exactly: supported or exempt
        # (the lint's runtime contract, also enforced by metrics_lint)
        assert "publish_to_deliver_us" in AdminServer._EXEMPLAR_FAMILIES
        assert not (AdminServer._EXEMPLAR_FAMILIES
                    & AdminServer._EXEMPLAR_EXEMPT)
    finally:
        await admin.stop()
        await server.stop()


# ---------------------------------------------------------------------------
# cross-cluster: one joined span tree over a federation link
# ---------------------------------------------------------------------------


async def test_federated_trace_joins_one_span_tree():
    """The ISSUE 20 acceptance walk: a client publishes with a
    traceparent on cluster A; the segment ships over the federation
    link; a consumer on cluster B receives it. The origin trace and the
    mirror trace must render as ONE OTLP tree under the client's trace
    id: client span -> origin broker root -> (stages) and origin root ->
    mirror root -> remote-apply/deliver."""
    a_srv, fed_a, b_srv, fed_b = await start_pair()
    rt = trace.install(TraceRuntime(
        sample_rate=0.0, metrics=a_srv.broker.metrics, node="cluster-a"))
    try:
        conn = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await conn.channel()
        await ch.confirm_select()
        await ch.queue_declare("fq", durable=True, arguments=STREAM_SMALL)
        props = BasicProperties(
            delivery_mode=2, headers={"traceparent": TRACEPARENT})
        for i in range(30):
            ch.basic_publish(f"f{i:06d}".encode(), routing_key="fq",
                             properties=props)
        await ch.wait_unconfirmed_below(1, timeout=15)
        sealed_tail = a_srv.broker.get_queue("/", "fq")._active_base
        assert sealed_tail > 1, "expected at least one sealed segment"
        await eventually(
            lambda: ("fq" in b_srv.broker.vhosts["/"].queues
                     and b_srv.broker.vhosts["/"].queues["fq"].next_offset
                     >= sealed_tail),
            what="mirror catch-up")
        b_queue = b_srv.broker.vhosts["/"].queues["fq"]
        # the apply path lifted the shipped contexts into mirror traces
        assert b_queue.fed_traces
        assert b_srv.broker.metrics.trace_ctx_recv >= sealed_tail - 1
        # stream-side origin traces completed at append (records are
        # copies; nothing settles the publish Message)
        origins = [t for t in rt.ring if t.slots[ENQUEUE] is not None
                   and t.slots[REMOTE_APPLY] is None]
        assert origins and all(t.w3c.trace_id == TID for t in origins)

        b_conn = await AMQPClient.connect("127.0.0.1", b_srv.bound_port)
        b_ch = await b_conn.channel()
        await b_ch.basic_qos(prefetch_count=64)
        got = await collect(b_ch, "fq", sealed_tail - 1)
        # the mirrored record still carries the ORIGIN's outgoing
        # traceparent (same trace id end to end)
        out = got[0].properties.headers["traceparent"]
        assert out.startswith(f"00-{TID}-") and out != TRACEPARENT
        await eventually(
            lambda: any(t.slots[REMOTE_APPLY] is not None
                        for t in rt.ring),
            what="mirror trace settle")
        mirrors = [t for t in rt.ring
                   if t.slots[REMOTE_APPLY] is not None]
        mirror = mirrors[0]
        assert mirror.w3c.trace_id == TID
        assert mirror.attrs["federated"] == "1"
        assert mirror.attrs["queue"] == "fq"
        assert mirror.slots[DELIVER] is not None  # consumer leg captured
        # THE join: the mirror's parent is some origin trace's root span
        origin_roots = {t.w3c.root_span_id for t in origins}
        assert mirror.w3c.parent_span_id in origin_roots
        origin = next(t for t in origins
                      if t.w3c.root_span_id == mirror.w3c.parent_span_id)
        # render both halves as one OTLP document and walk the tree:
        # producer -> origin root -> mirror root, all one trace id
        doc = resource_spans([origin, mirror],
                             default_resource(a_srv.broker))
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert {s["traceId"] for s in spans} == {TID}
        by_id = {s["spanId"]: s for s in spans}
        mirror_root = by_id[mirror.w3c.root_span_id]
        origin_root = by_id[origin.w3c.root_span_id]
        assert mirror_root["parentSpanId"] == origin_root["spanId"]
        assert origin_root["parentSpanId"] == SPAN  # the producer's span
        # every stage span hangs off its half's root
        for s in spans:
            if s["spanId"] in (origin_root["spanId"],
                               mirror_root["spanId"]):
                continue
            assert s["parentSpanId"] in (origin_root["spanId"],
                                         mirror_root["spanId"])
        await b_conn.close()
        await conn.close()
    finally:
        trace.clear()
        await stop_pair(a_srv, fed_a, b_srv, fed_b)


# ---------------------------------------------------------------------------
# log join key
# ---------------------------------------------------------------------------


async def test_logjson_carries_w3c_trace_id():
    import logging

    from chanamq_tpu.utils.logjson import JsonLogFormatter

    rt = trace.install(TraceRuntime(sample_rate=1.0, node="n1"))
    fmt = JsonLogFormatter()
    rec = logging.LogRecord("t", logging.INFO, "f", 1, "hello", None, None)
    # seeded sample: internal trace id only, no W3C join key
    rt.begin_publish()
    out = json.loads(fmt.format(rec))
    assert "trace" in out and "trace_id" not in out
    # propagated context: both ids appear
    rt.begin_publish(headers={"traceparent": TRACEPARENT})
    out = json.loads(fmt.format(rec))
    assert "trace" in out and out["trace_id"] == TID
    rt.current = None
    out = json.loads(fmt.format(rec))
    assert "trace" not in out and "trace_id" not in out
