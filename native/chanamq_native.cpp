// Native hot paths for chanamq_tpu: AMQP frame scanning and topic-trie
// routing.
//
// SURVEY.md §7.1 names the two measured hot paths worth a compiled
// implementation: (a) the frame parse loop (the reference's
// FrameParser.scala byte handling) and (b) the topic matcher (the
// reference's lock-free TrieMatcher, QueueMatcher.scala:140-601). Both are
// exposed through a minimal C ABI consumed via ctypes — no pybind11 in this
// image. The Python implementations remain as behavioral reference and
// fallback.
//
// Build: make -C native   ->  native/libchanamq_native.so

#include <cstdint>
#include <cstring>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// frame scanning
// ---------------------------------------------------------------------------

// Scan `buf` for complete AMQP frames (type u8 | channel u16be | size u32be |
// payload | 0xCE). Writes up to max_frames entries into the parallel output
// arrays. Returns the number of frames found.
//   *consumed  <- bytes covered by complete frames (caller trims its buffer)
//   *error     <- 0 ok; 1 unknown frame type; 2 frame exceeds frame_max;
//                 3 missing end octet
// On error, frames found before the error are still reported.
int chana_scan_frames(const uint8_t* buf, int64_t len, uint32_t frame_max,
                      int32_t* types, int32_t* channels, int64_t* offsets,
                      int64_t* lengths, int32_t max_frames, int64_t* consumed,
                      int32_t* error) {
  int n = 0;
  int64_t pos = 0;
  *error = 0;
  while (len - pos >= 7 && n < max_frames) {
    uint8_t type = buf[pos];
    if (type != 1 && type != 2 && type != 3 && type != 8) {
      *error = 1;
      break;
    }
    uint32_t channel = (uint32_t(buf[pos + 1]) << 8) | buf[pos + 2];
    uint32_t size = (uint32_t(buf[pos + 3]) << 24) |
                    (uint32_t(buf[pos + 4]) << 16) |
                    (uint32_t(buf[pos + 5]) << 8) | buf[pos + 6];
    if (frame_max != 0 && uint64_t(size) + 8 > frame_max) {
      *error = 2;
      break;
    }
    int64_t end = pos + 7 + int64_t(size);
    if (end + 1 > len) break;  // incomplete: wait for more bytes
    if (buf[end] != 0xCE) {
      *error = 3;
      break;
    }
    types[n] = type;
    channels[n] = int32_t(channel);
    offsets[n] = pos + 7;
    lengths[n] = int64_t(size);
    ++n;
    pos = end + 1;
  }
  *consumed = pos;
  return n;
}

// ---------------------------------------------------------------------------
// topic trie
// ---------------------------------------------------------------------------

namespace {

struct TrieNode {
  std::unordered_map<std::string, TrieNode*> children;
  std::set<int32_t> queues;

  ~TrieNode() {
    for (auto& kv : children) delete kv.second;
  }
};

struct Trie {
  TrieNode root;
  // (pattern, queue) registry for duplicate detection
  std::set<std::pair<std::string, int32_t>> bindings;
};

void split_words(const char* key, std::vector<std::string>* out) {
  const char* start = key;
  const char* p = key;
  for (;; ++p) {
    if (*p == '.' || *p == '\0') {
      out->emplace_back(start, p - start);
      if (*p == '\0') break;
      start = p + 1;
    }
  }
}

void walk(const TrieNode* node, const std::vector<std::string>& words,
          size_t i, std::unordered_set<int32_t>* out) {
  if (i == words.size()) {
    out->insert(node->queues.begin(), node->queues.end());
    // trailing '#' chains match zero remaining words
    const TrieNode* tail = node;
    for (;;) {
      auto it = tail->children.find("#");
      if (it == tail->children.end()) break;
      tail = it->second;
      out->insert(tail->queues.begin(), tail->queues.end());
    }
    return;
  }
  auto exact = node->children.find(words[i]);
  if (exact != node->children.end()) walk(exact->second, words, i + 1, out);
  auto star = node->children.find("*");
  if (star != node->children.end()) walk(star->second, words, i + 1, out);
  auto hash = node->children.find("#");
  if (hash != node->children.end()) {
    for (size_t j = i; j <= words.size(); ++j)
      walk(hash->second, words, j, out);
  }
}

}  // namespace

void* chana_trie_new() { return new Trie(); }

void chana_trie_free(void* handle) { delete static_cast<Trie*>(handle); }

// returns 1 when the binding was added, 0 when it already existed
int chana_trie_bind(void* handle, const char* pattern, int32_t queue_id) {
  Trie* trie = static_cast<Trie*>(handle);
  if (!trie->bindings.emplace(pattern, queue_id).second) return 0;
  std::vector<std::string> words;
  split_words(pattern, &words);
  TrieNode* node = &trie->root;
  for (const auto& word : words) {
    TrieNode*& child = node->children[word];
    if (child == nullptr) child = new TrieNode();
    node = child;
  }
  node->queues.insert(queue_id);
  return 1;
}

// returns 1 when the binding existed and was removed
int chana_trie_unbind(void* handle, const char* pattern, int32_t queue_id) {
  Trie* trie = static_cast<Trie*>(handle);
  if (trie->bindings.erase({pattern, queue_id}) == 0) return 0;
  std::vector<std::string> words;
  split_words(pattern, &words);
  // collect the path, then prune empty branches bottom-up (the reference's
  // tomb/contract step, QueueMatcher.scala:283-347)
  std::vector<std::pair<TrieNode*, std::string>> path;
  TrieNode* node = &trie->root;
  for (const auto& word : words) {
    auto it = node->children.find(word);
    if (it == node->children.end()) return 1;  // registry was authoritative
    path.emplace_back(node, word);
    node = it->second;
  }
  node->queues.erase(queue_id);
  for (auto it = path.rbegin(); it != path.rend(); ++it) {
    TrieNode* child = it->first->children[it->second];
    if (!child->queues.empty() || !child->children.empty()) break;
    it->first->children.erase(it->second);
    delete child;
  }
  return 1;
}

// routes `key`; writes up to max_out queue ids; returns the match count
int chana_trie_route(void* handle, const char* key, int32_t* out,
                     int32_t max_out) {
  Trie* trie = static_cast<Trie*>(handle);
  std::vector<std::string> words;
  split_words(key, &words);
  std::unordered_set<int32_t> matches;
  walk(&trie->root, words, 0, &matches);
  // Returns the TOTAL match count while writing at most max_out ids, so the
  // caller can detect truncation and retry with a larger buffer.
  int32_t n = 0;
  for (int32_t id : matches) {
    if (n < max_out) out[n] = id;
    n++;
  }
  return n;
}

int chana_trie_size(void* handle) {
  return int(static_cast<Trie*>(handle)->bindings.size());
}

// ---------------------------------------------------------------------------
// fused publish ingest: frame scan + METHOD/HEADER/BODY triple marking
// ---------------------------------------------------------------------------

// Superset of chana_scan_frames: after scanning, frames that start a
// complete Basic.Publish triple are marked so the Python loop touches one
// batch tuple instead of re-validating three frames per message.
//   pub_mark[i] <- frames the triple spans starting at i: 2 (empty body) or
//                  3 (single body frame); 0 = not a fusable publish here
//   body_off/body_len[i] <- span of the body inside buf (0/0 when empty)
// Shapes left unmarked (mandatory/immediate bits, channel 0, multi-frame
// bodies, interleaved channels, malformed shortstrs) fall back to the
// Python paths, which raise the proper protocol errors.
int chana_scan_publish(const uint8_t* buf, int64_t len, uint32_t frame_max,
                       int32_t* types, int32_t* channels, int64_t* offsets,
                       int64_t* lengths, int32_t* pub_mark, int64_t* body_off,
                       int64_t* body_len, int32_t max_frames,
                       int64_t* consumed, int32_t* error) {
  int n = chana_scan_frames(buf, len, frame_max, types, channels, offsets,
                            lengths, max_frames, consumed, error);
  for (int i = 0; i < n; ++i) {
    pub_mark[i] = 0;
    body_off[i] = 0;
    body_len[i] = 0;
  }
  for (int i = 0; i < n; ++i) {
    if (types[i] != 1 || channels[i] == 0) continue;
    int64_t sz = lengths[i];
    if (sz < 9) continue;  // sig(4) + reserved(2) + 2 shortstrs + bits
    const uint8_t* p = buf + offsets[i];
    // Basic.Publish: class 60, method 40
    if (p[0] != 0 || p[1] != 0x3c || p[2] != 0 || p[3] != 0x28) continue;
    int64_t pos = 6;  // past reserved-1 u16
    pos += 1 + p[pos];  // exchange shortstr
    if (pos >= sz) continue;
    pos += 1 + p[pos];  // routing-key shortstr
    if (pos >= sz) continue;
    uint8_t bits = p[pos];
    if (pos + 1 != sz || bits != 0) continue;  // mandatory/immediate/junk
    if (i + 1 >= n || types[i + 1] != 2 || channels[i + 1] != channels[i])
      continue;
    if (lengths[i + 1] < 14) continue;  // class+weight+body-size+flags
    const uint8_t* h = buf + offsets[i + 1];
    uint64_t bsz = 0;
    for (int k = 4; k < 12; ++k) bsz = (bsz << 8) | h[k];
    if (bsz == 0) {
      pub_mark[i] = 2;
      continue;
    }
    if (i + 2 >= n || types[i + 2] != 3 || channels[i + 2] != channels[i])
      continue;
    if (uint64_t(lengths[i + 2]) != bsz) continue;  // multi-frame body
    pub_mark[i] = 3;
    body_off[i] = offsets[i + 2];
    body_len[i] = int64_t(bsz);
  }
  return n;
}

// ---------------------------------------------------------------------------
// batch egress encode: N basic.deliver records -> one contiguous wire buffer
// ---------------------------------------------------------------------------

namespace {

inline uint8_t* put_frame_hdr(uint8_t* o, uint8_t type, uint32_t channel,
                              uint32_t size) {
  o[0] = type;
  o[1] = uint8_t(channel >> 8);
  o[2] = uint8_t(channel);
  o[3] = uint8_t(size >> 24);
  o[4] = uint8_t(size >> 16);
  o[5] = uint8_t(size >> 8);
  o[6] = uint8_t(size);
  return o + 7;
}

}  // namespace

// Encode n deliveries into `out`: per record a method frame
// (prefix | delivery-tag u64be | redelivered u8 | exrk), a content-header
// frame (pre-encoded header payload), and body frames split at
// frame_max - 8 (frame_max 0 = no splitting). Byte-identical to
// ServerChannel._render_deliver. Returns bytes written, or -1 when `cap`
// is too small (nothing partial is ever exposed: the caller sizes exactly).
int64_t chana_encode_deliveries(
    int32_t n, const int32_t* channels, const uint8_t* const* prefixes,
    const int32_t* prefix_lens, const uint64_t* tags,
    const uint8_t* redelivered, const uint8_t* const* exrks,
    const int32_t* exrk_lens, const uint8_t* const* headers,
    const int32_t* header_lens, const uint8_t* const* bodies,
    const int64_t* body_lens, uint32_t frame_max, uint8_t* out, int64_t cap) {
  uint8_t* o = out;
  const uint8_t* end = out + cap;
  for (int32_t r = 0; r < n; ++r) {
    uint32_t ch = uint32_t(channels[r]);
    int64_t mlen = int64_t(prefix_lens[r]) + 9 + exrk_lens[r];
    int64_t hlen = header_lens[r];
    int64_t blen = body_lens[r];
    int64_t maxp = frame_max != 0 ? int64_t(frame_max) - 8
                                  : (blen > 0 ? blen : 1);
    int64_t nchunks = blen ? (blen + maxp - 1) / maxp : 0;
    int64_t need = 8 + mlen + 8 + hlen + blen + 8 * nchunks;
    if (end - o < need) return -1;
    o = put_frame_hdr(o, 1, ch, uint32_t(mlen));
    std::memcpy(o, prefixes[r], prefix_lens[r]);
    o += prefix_lens[r];
    uint64_t tag = tags[r];
    for (int k = 7; k >= 0; --k) *o++ = uint8_t(tag >> (k * 8));
    *o++ = redelivered[r] ? 1 : 0;
    std::memcpy(o, exrks[r], exrk_lens[r]);
    o += exrk_lens[r];
    *o++ = 0xCE;
    o = put_frame_hdr(o, 2, ch, uint32_t(hlen));
    std::memcpy(o, headers[r], size_t(hlen));
    o += hlen;
    *o++ = 0xCE;
    const uint8_t* b = bodies[r];
    for (int64_t off = 0; off < blen; off += maxp) {
      int64_t chunk = blen - off < maxp ? blen - off : maxp;
      o = put_frame_hdr(o, 3, ch, uint32_t(chunk));
      std::memcpy(o, b + off, size_t(chunk));
      o += chunk;
      *o++ = 0xCE;
    }
  }
  return o - out;
}

// Packed-blob variant: the hot call. ctypes converts ONE bytes object per
// batch instead of four pointer arrays per record (each c_char_p element
// store costs ~250ns Python-side — more than the whole Python fallback
// encode for 100-byte messages). Blob layout per record:
//   meta (33 bytes, little-endian, packed):
//     int32 channel | uint64 tag | uint8 redelivered
//     int32 prefix_len | int32 exrk_len | int32 header_len | int64 body_len
//   then prefix || exrk || header || body, immediately following.
int64_t chana_encode_deliveries_packed(int32_t n, const uint8_t* blob,
                                       int64_t blob_len, uint32_t frame_max,
                                       uint8_t* out, int64_t cap) {
  const uint8_t* p = blob;
  const uint8_t* pend = blob + blob_len;
  uint8_t* o = out;
  const uint8_t* end = out + cap;
  for (int32_t r = 0; r < n; ++r) {
    if (pend - p < 33) return -1;
    int32_t ch, plen, elen, hlen;
    uint64_t tag;
    int64_t blen;
    uint8_t red;
    std::memcpy(&ch, p, 4);
    std::memcpy(&tag, p + 4, 8);
    red = p[12];
    std::memcpy(&plen, p + 13, 4);
    std::memcpy(&elen, p + 17, 4);
    std::memcpy(&hlen, p + 21, 4);
    std::memcpy(&blen, p + 25, 8);
    p += 33;
    if (pend - p < plen + elen + hlen + blen) return -1;
    int64_t mlen = int64_t(plen) + 9 + elen;
    int64_t maxp = frame_max != 0 ? int64_t(frame_max) - 8
                                  : (blen > 0 ? blen : 1);
    int64_t nchunks = blen ? (blen + maxp - 1) / maxp : 0;
    int64_t need = 8 + mlen + 8 + hlen + blen + 8 * nchunks;
    if (end - o < need) return -1;
    o = put_frame_hdr(o, 1, uint32_t(ch), uint32_t(mlen));
    std::memcpy(o, p, size_t(plen));
    o += plen;
    p += plen;
    for (int k = 7; k >= 0; --k) *o++ = uint8_t(tag >> (k * 8));
    *o++ = red ? 1 : 0;
    std::memcpy(o, p, size_t(elen));
    o += elen;
    p += elen;
    *o++ = 0xCE;
    o = put_frame_hdr(o, 2, uint32_t(ch), uint32_t(hlen));
    std::memcpy(o, p, size_t(hlen));
    o += hlen;
    p += hlen;
    *o++ = 0xCE;
    for (int64_t off = 0; off < blen; off += maxp) {
      int64_t chunk = blen - off < maxp ? blen - off : maxp;
      o = put_frame_hdr(o, 3, uint32_t(ch), uint32_t(chunk));
      std::memcpy(o, p + off, size_t(chunk));
      o += chunk;
      *o++ = 0xCE;
    }
    p += blen;
  }
  return o - out;
}

// ---------------------------------------------------------------------------
// egress buffer pool: reusable arenas so steady-state delivery allocates no
// per-message Python bytes. Python wraps each slot once as a writable
// memoryview; acquire/release just move slot ids on a free list.
// ---------------------------------------------------------------------------

namespace {

struct Pool {
  int64_t buf_size = 0;
  std::vector<uint8_t*> bufs;
  std::vector<int32_t> free_ids;
};

}  // namespace

void* chana_pool_new(int64_t buf_size, int32_t count) {
  Pool* pool = new Pool();
  pool->buf_size = buf_size;
  pool->bufs.reserve(count);
  pool->free_ids.reserve(count);
  for (int32_t i = 0; i < count; ++i) {
    pool->bufs.push_back(new uint8_t[buf_size]);
    pool->free_ids.push_back(count - 1 - i);  // slot 0 handed out first
  }
  return pool;
}

void chana_pool_destroy(void* handle) {
  Pool* pool = static_cast<Pool*>(handle);
  for (uint8_t* buf : pool->bufs) delete[] buf;
  delete pool;
}

// next free slot id, or -1 when the pool is exhausted (caller heap-allocs)
int32_t chana_pool_acquire(void* handle) {
  Pool* pool = static_cast<Pool*>(handle);
  if (pool->free_ids.empty()) return -1;
  int32_t id = pool->free_ids.back();
  pool->free_ids.pop_back();
  return id;
}

void chana_pool_release(void* handle, int32_t id) {
  static_cast<Pool*>(handle)->free_ids.push_back(id);
}

uint8_t* chana_pool_buf(void* handle, int32_t id) {
  return static_cast<Pool*>(handle)->bufs[id];
}

}  // extern "C"
