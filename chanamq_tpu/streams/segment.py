"""Segmented append-only log primitives for stream queues.

A stream is a sequence of immutable records partitioned into segments
(Pulsar/RabbitMQ-streams layout, PAPERS.md "1.5 Million Messages Per
Second on 3 Machines"): one mutable *active* segment accepts appends;
once it crosses a size/age threshold it is *sealed* — frozen, encoded to
a single blob, and spilled to the store. Sealed segments are the unit of
retention (whole-segment truncation) and of persistence (one store row
per segment instead of one per message).

Record wire layout inside a segment blob, repeated back to back:

    offset        uint64    stream offset (monotonic from 1)
    ts_ms         uint64    broker append time, epoch milliseconds
    exchange_len  uint16    + utf-8 exchange name
    rkey_len      uint16    + utf-8 routing key
    header_len    uint32    + content-header frame payload (wire format)
    body_len      uint32    + body bytes

The content header is stored as the raw frame payload so replay delivers
byte-identical property frames without a decode/encode round trip.
"""

from __future__ import annotations

import struct
from typing import Optional

_FIXED = struct.Struct(">QQHHII")
_FIXED_SIZE = _FIXED.size


class StreamRecord:
    """One immutable record in a stream."""

    __slots__ = ("offset", "ts_ms", "exchange", "routing_key",
                 "header_raw", "body")

    def __init__(
        self,
        offset: int,
        ts_ms: int,
        exchange: str,
        routing_key: str,
        header_raw: bytes,
        body: bytes,
    ) -> None:
        self.offset = offset
        self.ts_ms = ts_ms
        self.exchange = exchange
        self.routing_key = routing_key
        self.header_raw = header_raw
        self.body = body

    @property
    def wire_size(self) -> int:
        """Encoded size; the unit of every stream byte limit so active and
        sealed segments account identically."""
        return (_FIXED_SIZE + len(self.exchange.encode())
                + len(self.routing_key.encode())
                + len(self.header_raw) + len(self.body))


class Segment:
    """A sealed segment's metadata (+ its records while cached resident).

    records is None when the segment has been evicted from RAM; the blob
    is reloaded from the store on the first cursor that reads into it.
    """

    __slots__ = ("base_offset", "last_offset", "first_ts_ms", "last_ts_ms",
                 "size_bytes", "records")

    def __init__(
        self,
        base_offset: int,
        last_offset: int,
        first_ts_ms: int,
        last_ts_ms: int,
        size_bytes: int,
        records: Optional[list[StreamRecord]] = None,
    ) -> None:
        self.base_offset = base_offset
        self.last_offset = last_offset
        self.first_ts_ms = first_ts_ms
        self.last_ts_ms = last_ts_ms
        self.size_bytes = size_bytes
        self.records = records


def pack_records(records: list[StreamRecord]) -> bytes:
    out = bytearray()
    for rec in records:
        exchange = rec.exchange.encode()
        rkey = rec.routing_key.encode()
        out += _FIXED.pack(rec.offset, rec.ts_ms, len(exchange), len(rkey),
                           len(rec.header_raw), len(rec.body))
        out += exchange
        out += rkey
        out += rec.header_raw
        out += rec.body
    return bytes(out)


def unpack_records_indexed(
    blob: bytes, base_offset: int, last_offset: int
) -> "list[Optional[StreamRecord]]":
    """Slot list covering base..last inclusive, indexed by offset-base.

    The record wire format carries explicit offsets, so a blob that key
    compaction made *sparse* (chanamq_tpu/wal/tier.py) reconstructs with
    None holes where records were dropped — the read paths index
    ``records[offset - base_offset]`` and skip the holes, keeping every
    committed cursor offset valid across compaction.  A dense blob fills
    every slot and behaves exactly as before.
    """
    slots: "list[Optional[StreamRecord]]" = (
        [None] * (last_offset - base_offset + 1))
    for rec in unpack_records(blob):
        idx = rec.offset - base_offset
        if 0 <= idx < len(slots):
            slots[idx] = rec
    return slots


def unpack_records(blob: bytes) -> list[StreamRecord]:
    records: list[StreamRecord] = []
    pos = 0
    end = len(blob)
    while pos < end:
        offset, ts_ms, elen, rlen, hlen, blen = _FIXED.unpack_from(blob, pos)
        pos += _FIXED_SIZE
        exchange = blob[pos:pos + elen].decode()
        pos += elen
        rkey = blob[pos:pos + rlen].decode()
        pos += rlen
        header_raw = blob[pos:pos + hlen]
        pos += hlen
        body = blob[pos:pos + blen]
        pos += blen
        records.append(
            StreamRecord(offset, ts_ms, exchange, rkey, header_raw, body))
    return records
