"""Native fused ingress->egress pipeline parity (PR 18).

The native pipeline must be invisible on the wire: chana_encode_deliveries
output is byte-identical to the pure-Python Frame rendering for every body
size (empty, straddling frame-max splits) and header permutation, the pool
exhaustion path falls back to heap encode with identical bytes, and
chana_scan_publish marks exactly the complete Basic.Publish triples the
fused fast path may consume.  A CHANAMQ_NATIVE=0 twin run of a confirm +
consume scenario asserts identical confirm/delivery ordering end to end.
"""

import ctypes
import os
import random
import subprocess
import sys

import pytest

from chanamq_tpu import native_ext
from chanamq_tpu.amqp.frame import Frame, deliveries_wire_size, encode_deliveries
from chanamq_tpu.amqp.properties import BasicProperties

pytestmark = pytest.mark.skipif(
    not native_ext.pipeline_available(),
    reason="native pipeline unavailable")


def shortstr(s: bytes) -> bytes:
    return bytes([len(s)]) + s


def make_record(rng: random.Random, body: bytes, props: BasicProperties,
                channel: int | None = None) -> tuple:
    """One (channel_id, prefix, tag, redelivered, exrk, header, body)
    delivery record with randomized identifiers."""
    ctag = b"ctag-" + str(rng.randrange(10 ** 6)).encode()
    prefix = b"\x00\x3c\x00\x3c" + shortstr(ctag)
    exrk = shortstr(b"amq.topic") + shortstr(
        b"rk." + str(rng.randrange(1000)).encode())
    return (
        channel if channel is not None else rng.randrange(1, 2048),
        prefix,
        rng.randrange(1, 2 ** 63),
        rng.random() < 0.5,
        exrk,
        props.encode_header(len(body)),
        body,
    )


def reference_wire(records: list, frame_max: int) -> bytes:
    """Third, independent rendering built frame-by-frame from Frame()."""
    maxp = frame_max - 8 if frame_max else 0
    out = []
    for cid, prefix, tag, red, exrk, header, body in records:
        method = (prefix + tag.to_bytes(8, "big")
                  + (b"\x01" if red else b"\x00") + exrk)
        out.append(Frame.method(cid, method).to_bytes())
        out.append(Frame.header(cid, header).to_bytes())
        if body:
            step = maxp if frame_max else len(body)
            for off in range(0, len(body), step):
                out.append(Frame.body(cid, body[off:off + step]).to_bytes())
    return b"".join(out)


HEADER_PERMUTATIONS = [
    BasicProperties(),
    BasicProperties(delivery_mode=2),
    BasicProperties(content_type="application/json", delivery_mode=1),
    BasicProperties(priority=7, correlation_id="c" * 40, reply_to="amq.rpc"),
    BasicProperties(expiration="60000", message_id="m-1",
                    timestamp=1_700_000_000, type="event"),
    BasicProperties(user_id="guest", app_id="bench",
                    headers={"x-key": "value", "n": 42}),
]


def fresh_encoder(pool_buffers: int = 4,
                  pool_buffer_bytes: int = 64 * 1024):
    return native_ext.NativeEgressEncoder(pool_buffers, pool_buffer_bytes)


def encode_native(enc, records: list, frame_max: int) -> bytes:
    nbytes = deliveries_wire_size(records, frame_max)
    res = enc.encode(records, frame_max, nbytes)
    assert res is not None, "native encode disagreed with wire-size"
    buf, slot = res
    data = bytes(buf)
    if slot >= 0:
        enc.release(slot)
    return data


# ---------------------------------------------------------------------------
# batch egress encode parity
# ---------------------------------------------------------------------------


def test_encode_deliveries_parity_fuzz():
    """Random batches: native == pure-Python == frame-by-frame reference,
    byte for byte, across body sizes straddling every split boundary."""
    rng = random.Random(0xC0FFEE)
    enc = fresh_encoder()
    for frame_max in (0, 64, 4096, 131072):
        maxp = frame_max - 8 if frame_max else 0
        boundary_sizes = [0, 1, 17]
        if frame_max:
            boundary_sizes += [maxp - 1, maxp, maxp + 1,
                               2 * maxp, 3 * maxp + 7]
        for trial in range(8):
            records = []
            for size in boundary_sizes:
                body = bytes(rng.randrange(256) for _ in range(size))
                records.append(make_record(
                    rng, body, rng.choice(HEADER_PERMUTATIONS)))
            rng.shuffle(records)
            expected = encode_deliveries(records, frame_max)
            assert expected == reference_wire(records, frame_max)
            assert len(expected) == deliveries_wire_size(records, frame_max)
            assert encode_native(enc, records, frame_max) == expected


def test_encode_header_permutations_single_record():
    rng = random.Random(7)
    enc = fresh_encoder()
    for props in HEADER_PERMUTATIONS:
        records = [make_record(rng, b"payload", props, channel=3)]
        expected = reference_wire(records, 4096)
        assert encode_deliveries(records, 4096) == expected
        assert encode_native(enc, records, 4096) == expected


def test_encode_empty_batch_and_empty_bodies():
    rng = random.Random(11)
    enc = fresh_encoder()
    records = [make_record(rng, b"", BasicProperties()) for _ in range(5)]
    expected = encode_deliveries(records, 4096)
    assert encode_native(enc, records, 4096) == expected
    # no body frames at all: wire is exactly method+header pairs
    assert expected.count(b"\xce") >= 10


def test_encode_memoryview_fields():
    """Cluster/stream paths hand memoryview headers and bodies — the
    native encoder must accept them with identical output."""
    rng = random.Random(13)
    enc = fresh_encoder()
    base = make_record(rng, b"x" * 5000, BasicProperties(delivery_mode=2))
    cid, prefix, tag, red, exrk, header, body = base
    mv_record = (cid, prefix, tag, red, exrk,
                 memoryview(bytes(header)), memoryview(bytes(body)))
    for frame_max in (0, 4096):
        expected = encode_deliveries([base], frame_max)
        assert encode_native(enc, [mv_record], frame_max) == expected


def test_pool_exhaustion_heap_fallback_is_byte_identical():
    """With every arena slot held, encode lands in a fresh bytearray
    (slot -1) with the same bytes; released slots are reused."""
    enc = fresh_encoder(pool_buffers=2, pool_buffer_bytes=16 * 1024)
    rng = random.Random(17)
    records = [make_record(rng, b"b" * 512, BasicProperties())
               for _ in range(4)]
    nbytes = deliveries_wire_size(records, 4096)
    expected = encode_deliveries(records, 4096)

    buf1, slot1 = enc.encode(records, 4096, nbytes)
    buf2, slot2 = enc.encode(records, 4096, nbytes)
    assert slot1 >= 0 and slot2 >= 0 and slot1 != slot2
    assert bytes(buf1) == bytes(buf2) == expected
    # pool dry: heap fallback, still byte-identical
    buf3, slot3 = enc.encode(records, 4096, nbytes)
    assert slot3 == -1 and isinstance(buf3, bytearray)
    assert bytes(buf3) == expected
    enc.release(slot1)
    enc.release(slot2)
    buf4, slot4 = enc.encode(records, 4096, nbytes)
    assert slot4 >= 0
    assert bytes(buf4) == expected
    enc.release(slot4)


def test_oversized_batch_skips_pool():
    """A batch larger than one arena buffer must heap-encode, not
    truncate."""
    enc = fresh_encoder(pool_buffers=2, pool_buffer_bytes=4 * 1024)
    rng = random.Random(19)
    records = [make_record(rng, b"z" * 9000, BasicProperties())]
    nbytes = deliveries_wire_size(records, 4096)
    buf, slot = enc.encode(records, 4096, nbytes)
    assert slot == -1
    assert bytes(buf) == encode_deliveries(records, 4096)


# ---------------------------------------------------------------------------
# fused publish scan marks
# ---------------------------------------------------------------------------


def publish_frames(channel: int, exchange: bytes, rk: bytes, body: bytes,
                   *, frame_max: int = 0, bits: int = 0) -> bytes:
    """Hand-assembled Basic.Publish method+header+body wire bytes."""
    method = (b"\x00\x3c\x00\x28\x00\x00"
              + shortstr(exchange) + shortstr(rk) + bytes([bits]))
    header = BasicProperties().encode_header(len(body))
    wire = (Frame.method(channel, method).to_bytes()
            + Frame.header(channel, header).to_bytes())
    if body:
        step = frame_max - 8 if frame_max else len(body)
        for off in range(0, len(body), step):
            wire += Frame.body(channel, body[off:off + step]).to_bytes()
    return wire


def scan_marks(wire: bytes):
    parser = native_ext.NativeFrameParser(frame_max=0)
    batches = list(parser.scan_batches(wire))
    assert len(batches) == 1
    raw, n, types, channels, offsets, lengths, pub_mark, body_off, body_len \
        = batches[0]
    return raw, n, list(pub_mark[:n]), list(body_off[:n]), list(body_len[:n])


def test_scan_publish_marks_single_body_triple():
    body = b"hello fused world"
    wire = publish_frames(5, b"", b"q1", body)
    raw, n, marks, boffs, blens = scan_marks(wire)
    assert n == 3
    assert marks == [3, 0, 0]
    assert raw[boffs[0]:boffs[0] + blens[0]] == body


def test_scan_publish_marks_empty_body():
    wire = publish_frames(2, b"amq.topic", b"a.b", b"")
    raw, n, marks, _boffs, _blens = scan_marks(wire)
    assert n == 2
    assert marks == [2, 0]


def test_scan_publish_no_mark_with_mandatory_bit():
    # mandatory/immediate publishes take the slow path (they need the
    # full decode for basic.return handling)
    wire = publish_frames(1, b"", b"q", b"x", bits=1)
    _raw, n, marks, _o, _l = scan_marks(wire)
    assert n == 3
    assert marks == [0, 0, 0]


def test_scan_publish_no_mark_for_multiframe_body():
    body = b"m" * 300
    wire = publish_frames(1, b"", b"q", body, frame_max=136)  # 128B chunks
    _raw, n, marks, _o, _l = scan_marks(wire)
    assert n == 2 + 3  # method + header + 3 body chunks
    assert marks == [0] * n


def test_scan_publish_back_to_back_triples():
    wire = (publish_frames(1, b"", b"qa", b"one")
            + publish_frames(7, b"amq.direct", b"k", b"")
            + publish_frames(1, b"", b"qb", b"three"))
    raw, n, marks, boffs, blens = scan_marks(wire)
    assert n == 8
    assert marks == [3, 0, 0, 2, 0, 3, 0, 0]
    assert raw[boffs[0]:boffs[0] + blens[0]] == b"one"
    assert raw[boffs[5]:boffs[5] + blens[5]] == b"three"


def test_scan_publish_mark_requires_complete_triple():
    """A publish whose body frame has not arrived yet must NOT be marked
    (the fused path would read past the scanned window)."""
    wire = publish_frames(1, b"", b"q", b"tail-cut")
    # cut mid body frame: scanner sees method+header complete, body partial
    cut = wire[:len(wire) - 4]
    parser = native_ext.NativeFrameParser(frame_max=0)
    out = list(parser.scan_batches(cut))
    assert len(out) == 1
    _raw, n, _t, _c, _o, _l, marks, _bo, _bl = out[0]
    assert n == 2
    assert list(marks[:n]) == [0, 0]


# ---------------------------------------------------------------------------
# CHANAMQ_NATIVE=0 twin: identical confirm/delivery ordering end to end
# ---------------------------------------------------------------------------


TWIN_SCRIPT = r"""
import asyncio, os, sys
sys.path.insert(0, {repo!r})
from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient

async def main():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    ch = await c.channel()
    await ch.queue_declare("twin_q")
    await ch.confirm_select()
    confirms = []
    deliveries = []
    done = asyncio.Event()
    N = 40
    def cb(msg):
        deliveries.append((msg.delivery_tag, bytes(msg.body)[:16],
                           len(msg.body)))
        if len(deliveries) == N:
            done.set()
    await ch.basic_consume("twin_q", cb, no_ack=True)
    # mixed sizes: empty, small, multi-frame (> frame_max)
    sizes = [0, 1, 17, 1024, 200000, 5, 131064, 131065, 64, 0]
    for i in range(N):
        body = bytes([i % 251]) * sizes[i % len(sizes)]
        await ch.basic_publish_confirmed(
            body, routing_key="twin_q",
            properties=BasicProperties(message_id=str(i)))
        confirms.append(i)
    await asyncio.wait_for(done.wait(), 20)
    for tag, head, blen in deliveries:
        print("D", tag, head.hex(), blen)
    print("C", ",".join(map(str, confirms)))
    await c.close()
    await srv.stop()

asyncio.run(main())
"""


def test_native_vs_python_twin_ordering(tmp_path):
    script = tmp_path / "twin.py"
    script.write_text(TWIN_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outputs = {}
    for native in ("1", "0"):
        env = dict(os.environ, CHANAMQ_NATIVE=native, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outputs[native] = proc.stdout
    assert outputs["1"] == outputs["0"]
    assert outputs["1"].count("\nC ") or outputs["1"].startswith("C ") or \
        "C " in outputs["1"]
