#!/usr/bin/env python3
"""Broker benchmark harness — the reference's PerfTest matrix, multi-process.

Reproduces the shape of the reference's perf specs
(chana-mq-test/perf/publish-consume-spec*.js: {autoAck, manual-ack} x
{transient, persistent}, 3 producers, 3 consumers transient / 1 consumer
persistent, prefetch 5000) against this broker. Like the reference's
RabbitMQ PerfTest, every producer/consumer is its OWN process talking to the
broker process over real sockets, publishers pace themselves with a
publisher-confirm window, and latency is measured client-side from a
timestamp embedded in the message body (publish -> deliver, end to end).

Prints ONE JSON line:
  {"metric": ..., "value": msgs/s, "unit": "msgs/s", "vs_baseline": null, ...}
vs_baseline is null because the reference publishes no numbers
(BASELINE.md: "harness only").

Env knobs: BENCH_SECONDS (default 5), BENCH_BODY_BYTES (default 100),
BENCH_SPECS ("a" = headline transient/autoAck only, "all" = full matrix),
BENCH_CONFIRM_WINDOW (default 2000).
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BENCH_SECONDS = float(os.environ.get("BENCH_SECONDS", "5"))
BODY_BYTES = max(16, int(os.environ.get("BENCH_BODY_BYTES", "100")))
CONFIRM_WINDOW = int(os.environ.get("BENCH_CONFIRM_WINDOW", "2000"))
PREFETCH = 5000

SPECS = {
    # name -> (auto_ack, persistent, producers, consumers); mirrors the
    # reference's four spec files
    "transient_autoack_3p3c": (True, False, 3, 3),
    "transient_ack_3p3c": (False, False, 3, 3),
    # same-topology transient twins of the persistent specs: the honest
    # denominators for the WAL overhead ratio (--wal)
    "transient_autoack_3p1c": (True, False, 3, 1),
    "transient_ack_3p1c": (False, False, 3, 1),
    "persistent_autoack_3p1c": (True, True, 3, 1),
    "persistent_ack_3p1c": (False, True, 3, 1),
}

# the remaining BASELINE.json configs: fanout 1 producer -> 8 consumers and
# a topic exchange with wildcard bindings over mixed routing keys (one
# consumer per queue; delivered counts every copy, like PerfTest)
TOPO_SPECS = {
    "fanout_1p8c": {
        "exchange_type": "fanout", "producers": 1,
        "queues": [(f"bench_q{i}", [""]) for i in range(8)],
        "keys": ["bench"],
    },
    "topic_3p3c_wildcards": {
        "exchange_type": "topic", "producers": 3,
        "queues": [("bench_q0", ["quote.*.*"]),
                   ("bench_q1", ["quote.#", "*.eu.msft"]),
                   ("bench_q2", ["#"])],
        "keys": ["quote.us.appl", "quote.eu.msft", "trade.us.goog"],
    },
}

# Paced-load latency spec: the saturated specs above measure queueing delay
# by construction (a full confirm window IS hundreds of ms of in-flight
# messages), so broker latency is measured separately under a fixed-rate
# load well below capacity. The rate is derived from the measured headline
# (~25% of saturated throughput) or BENCH_PACED_RATE.
PACED_SPEC = "paced_latency_1p1c"
PACED_PERSISTENT_SPEC = "paced_persistent_latency_1p1c"


# ---------------------------------------------------------------------------
# child roles
# ---------------------------------------------------------------------------


async def producer_main(
    port: int, persistent: bool, seconds: float, rate: int = 0,
    keys: "list[str] | None" = None, shape: str = "burst",
) -> None:
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.client import AMQPClient

    keys = keys or ["bench"]
    nkeys = len(keys)
    c = await AMQPClient.connect("127.0.0.1", port)
    ch = await c.channel()
    await ch.confirm_select()
    props = BasicProperties(delivery_mode=2 if persistent else 1)
    pad = b"x" * (BODY_BYTES - 8)
    deadline = time.perf_counter() + seconds
    published = 0
    if rate > 0:
        # fixed-rate pacing: 10 ms micro-bursts (PerfTest --rate shape) by
        # default, or strictly per-message ("smooth") — the burst shape
        # queues up to rate/100 messages at each tick, so its measured p99
        # has a ~10 ms floor that buries sub-ms broker latency
        burst = 1 if shape == "smooth" else max(1, rate // 100)
        next_t = time.perf_counter()
        while time.perf_counter() < deadline:
            for _ in range(burst):
                body = time.time_ns().to_bytes(8, "big") + pad
                ch.basic_publish(body, exchange="bench_ex",
                                 routing_key=keys[published % nkeys],
                                 properties=props)
                published += 1
            next_t += burst / rate
            delay = next_t - time.perf_counter()
            if delay > 0:
                await c.drain()
                await asyncio.sleep(delay)
            if len(ch.unconfirmed) >= CONFIRM_WINDOW:
                await c.drain()
                await ch.wait_unconfirmed_below(CONFIRM_WINDOW // 2)
    else:
        while time.perf_counter() < deadline:
            body = time.time_ns().to_bytes(8, "big") + pad
            ch.basic_publish(body, exchange="bench_ex",
                             routing_key=keys[published % nkeys],
                             properties=props)
            published += 1
            if len(ch.unconfirmed) >= CONFIRM_WINDOW:
                await c.drain()
                await ch.wait_unconfirmed_below(CONFIRM_WINDOW // 2)
    await c.drain()
    try:
        await ch.wait_unconfirmed_below(1, timeout=15)
    except asyncio.TimeoutError:
        pass
    await c.close()
    print(json.dumps({"role": "producer", "published": published}), flush=True)


async def consumer_main(port: int, auto_ack: bool, seconds: float,
                        queue: str = "bench_q") -> None:
    from chanamq_tpu.client import AMQPClient

    c = await AMQPClient.connect("127.0.0.1", port)
    ch = await c.channel()
    if not auto_ack:
        await ch.basic_qos(prefetch_count=PREFETCH)
    delivered = 0
    latencies: list[int] = []

    def on_msg(msg) -> None:
        nonlocal delivered
        delivered += 1
        latencies.append(time.time_ns() - int.from_bytes(msg.body[:8], "big"))
        if not auto_ack and delivered % 500 == 0:
            ch.basic_ack(msg.delivery_tag, multiple=True)

    await ch.basic_consume(queue, on_msg, no_ack=auto_ack)
    # run until producers are done plus drain time
    await asyncio.sleep(seconds + 3)
    if not auto_ack and delivered:
        ch.basic_ack(0, multiple=True)
        await asyncio.sleep(0.2)
    await c.close()
    latencies.sort()
    n = len(latencies)
    stats = {
        "role": "consumer",
        "delivered": delivered,
        "p50_us": latencies[n // 2] / 1000 if n else None,
        "p99_us": latencies[min(n - 1, int(n * 0.99))] / 1000 if n else None,
        "max_us": latencies[-1] / 1000 if n else None,
    }
    print(json.dumps(stats), flush=True)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------


def wait_port(port: int, timeout: float = 15) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.2).close()
            return
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("broker did not come up")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def setup_topology(
    port: int, persistent: bool, exchange_type: str = "direct",
    queues: "list[tuple[str, list[str]]] | None" = None,
) -> None:
    from chanamq_tpu.client import AMQPClient

    queues = queues or [("bench_q", ["bench"])]
    c = await AMQPClient.connect("127.0.0.1", port)
    ch = await c.channel()
    await ch.exchange_declare("bench_ex", exchange_type, durable=persistent)
    for name, bind_keys in queues:
        await ch.queue_declare(name, durable=persistent)
        for key in bind_keys:
            await ch.queue_bind(name, "bench_ex", key)
    await c.close()


def _tail(path: str, limit: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode("utf-8", "replace").strip()
    except OSError:
        return ""


def _reap_children(children: list, consumers: int,
                   timeout: float) -> "tuple[list[dict], list[str]]":
    """Collect each child's one-line JSON result (consumers first, then
    producers, matching spawn order); kills and reports stragglers."""
    outputs: list[dict] = []
    errors: list[str] = []
    for i, child in enumerate(children):
        role = "consumer" if i < consumers else "producer"
        try:
            out, err = child.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            child.kill()
            _, err = child.communicate()
            err_lines = err.decode("utf-8", "replace").strip().splitlines()
            tail = f": {err_lines[-1][:300]}" if err_lines else ""
            errors.append(f"{role}[{i}] timed out{tail}")
            continue  # post-kill partial stdout is not a valid result
        lines = out.decode().strip().splitlines()
        if child.returncode != 0 or not lines:
            err_lines = err.decode("utf-8", "replace").strip().splitlines()
            tail = err_lines[-1][:300] if err_lines else "no output"
            errors.append(f"{role}[{i}] rc={child.returncode}: {tail}")
            continue
        try:
            outputs.append(json.loads(lines[-1]))
        except ValueError:
            errors.append(f"{role}[{i}] bad output: {lines[-1][:200]}")
    return outputs, errors


def _proc_cpu_s(pid: int) -> "float | None":
    """Cumulative user+system CPU seconds of a process from
    /proc/<pid>/stat. Sampled around the load window so boot cost (JAX
    import is seconds) never pollutes the per-message CPU figure."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read().decode("ascii", "replace")
        # comm may contain spaces/parens; real fields start after the
        # last ')': state is field 3, utime/stime are fields 14/15
        fields = data.rpartition(")")[2].split()
        ticks = int(fields[11]) + int(fields[12])
        return ticks / os.sysconf("SC_CLK_TCK")
    except (OSError, IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# bench trajectory ledger + regression gate
# ---------------------------------------------------------------------------

TRAJECTORY_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_trajectory.jsonl")


def _git_rev() -> "str | None":
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, timeout=10)
        return out.stdout.decode().strip() or None
    except Exception:
        return None


def _env_fingerprint() -> dict:
    """What must match for two trajectory lines to be comparable: numbers
    from a different core count, body size, run length, or parser
    implementation are history, not baselines."""
    from chanamq_tpu import native_ext

    return {
        "python": sys.version.split()[0],
        "cores": os.cpu_count(),
        "body_bytes": BODY_BYTES,
        "seconds": BENCH_SECONDS,
        "native": native_ext.available(),
    }


def trajectory_record(scenario: str, result: dict) -> "dict | None":
    """Normalize one clean run_spec result into a trajectory line. The
    headline cost is µs of wall per delivered message; cpu_us_per_msg is
    the broker-process CPU ledger (far less noisy than wall on a shared
    box, hence the tighter regression band on it)."""
    delivered_per_s = result.get("delivered_per_s")
    if not delivered_per_s:
        return None
    return {
        "ts": round(time.time(), 1),
        "scenario": scenario,
        "us_per_msg": round(1e6 / delivered_per_s, 3),
        "cpu_us_per_msg": result.get("cpu_us_per_msg"),
        "delivered_per_s": delivered_per_s,
        "p50_us": result.get("p50_us"),
        "p99_us": result.get("p99_us"),
        "rev": _git_rev(),
        "env": _env_fingerprint(),
    }


def trajectory_append(record: dict) -> None:
    with open(TRAJECTORY_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")


def trajectory_baseline(scenario: str,
                        path: str = None,
                        stats: "dict | None" = None) -> "dict | None":
    """Latest recorded run of `scenario` from a comparable environment.

    When `stats` is given, stats["corrupt_lines"] counts unparseable
    lines skipped along the way — a half-written append from a killed
    run must not silently shrink the judged history."""
    env = _env_fingerprint()
    latest = None
    corrupt = 0
    try:
        with open(path or TRAJECTORY_PATH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    corrupt += 1
                    continue
                if rec.get("scenario") != scenario:
                    continue
                rec_env = rec.get("env") or {}
                if any(rec_env.get(k) != env[k]
                       for k in ("cores", "body_bytes", "seconds",
                                 "native")):
                    continue
                latest = rec
    except OSError:
        if stats is not None:
            stats["corrupt_lines"] = corrupt
        return None
    if stats is not None:
        stats["corrupt_lines"] = corrupt
    return latest


def regress_evaluate(current: dict, base: dict,
                     wall_band: float = 0.20,
                     cpu_band: float = 0.10) -> dict:
    """Pure verdict on one scenario (unit-testable without a broker).

    Regressed only when BOTH per-message costs exceed their noise band:
    wall µs/msg past +20% (the ROADMAP's honest band for 5 s wall numbers
    on a shared box) AND broker CPU µs/msg past +10% (CPU is steadier, so
    the band is tighter). Requiring both keeps a CPU-steal burst in either
    single run from failing the gate; a real regression moves both. Wall
    alone decides when either side lacks the CPU ledger (old record)."""
    cur_wall, base_wall = current.get("us_per_msg"), base.get("us_per_msg")
    cur_cpu, base_cpu = (current.get("cpu_us_per_msg"),
                         base.get("cpu_us_per_msg"))
    wall_over = bool(cur_wall is not None and base_wall
                     and cur_wall > base_wall * (1 + wall_band))
    cpu_over = bool(cur_cpu is not None and base_cpu
                    and cur_cpu > base_cpu * (1 + cpu_band))
    if cur_cpu is None or not base_cpu:
        regressed = wall_over
    else:
        regressed = wall_over and cpu_over
    return {
        "scenario": current.get("scenario"),
        "us_per_msg": cur_wall,
        "base_us_per_msg": base_wall,
        "cpu_us_per_msg": cur_cpu,
        "base_cpu_us_per_msg": base_cpu,
        "wall_band_pct": round(wall_band * 100, 1),
        "cpu_band_pct": round(cpu_band * 100, 1),
        "wall_over": wall_over,
        "cpu_over": cpu_over,
        "base_rev": base.get("rev"),
        "base_ts": base.get("ts"),
        "regressed": regressed,
    }


def run_spec(name: str, rate: int = 0,
             extra_env: "dict | None" = None,
             shape: str = "burst") -> dict:
    persistent = False
    exchange_type = "direct"
    queues = None  # default bench_q/bench
    keys = None
    if name == PACED_SPEC:
        auto_ack, producers, consumers = True, 1, 1
    elif name == PACED_PERSISTENT_SPEC:
        # durable-path latency: publish->deliver through the group-commit
        # store at a rate well below persistent capacity
        auto_ack, producers, consumers = True, 1, 1
        persistent = True
    elif name in TOPO_SPECS:
        topo = TOPO_SPECS[name]
        auto_ack = True
        producers = topo["producers"]
        exchange_type = topo["exchange_type"]
        queues = topo["queues"]
        keys = topo["keys"]
        consumers = len(queues)
    else:
        auto_ack, persistent, producers, consumers = SPECS[name]
    port = free_port()
    env = {**os.environ, "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))}
    if extra_env:
        env.update(extra_env)
    broker_args = [sys.executable, "-m", "chanamq_tpu.broker.server",
                   "--host", "127.0.0.1", "--port", str(port),
                   "--no-admin", "--log-level", "WARNING"]
    store_file = None
    if persistent:
        tmp = tempfile.NamedTemporaryFile(suffix=".db", delete=False)
        tmp.close()
        store_file = tmp.name
        broker_args += ["--store", store_file]
    # Broker stderr goes to a file so a failed spec can report the tail
    # instead of an opaque crash (the round-2 postmortem's ask).
    broker_log = tempfile.NamedTemporaryFile(
        suffix=".log", prefix="bench-broker-", delete=False)
    broker = subprocess.Popen(broker_args, env=env,
                              stdout=broker_log, stderr=broker_log)
    children = []
    errors: list[str] = []
    outputs: list[dict] = []
    elapsed = 0.0
    cpu0 = cpu1 = None
    try:
        wait_port(port)
        asyncio.run(setup_topology(port, persistent, exchange_type, queues))
        # broker CPU around the load window only: boot (JAX import) and
        # teardown are excluded from the per-message figure
        cpu0 = _proc_cpu_s(broker.pid)
        queue_names = [q for q, _ in queues] if queues else ["bench_q"]
        for i in range(consumers):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "consumer",
                 "--port", str(port), "--auto-ack", str(int(auto_ack)),
                 "--seconds", str(BENCH_SECONDS),
                 "--queue", queue_names[i % len(queue_names)]],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        time.sleep(0.3)
        t0 = time.perf_counter()
        producer_args = []
        if keys:
            producer_args = ["--keys", ",".join(keys)]
        for _ in range(producers):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "producer",
                 "--port", str(port), "--persistent", str(int(persistent)),
                 "--seconds", str(BENCH_SECONDS), "--rate", str(rate),
                 "--shape", shape]
                + producer_args,
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        outs, errs = _reap_children(children, consumers, BENCH_SECONDS + 60)
        outputs.extend(outs)
        errors.extend(errs)
        elapsed = time.perf_counter() - t0
        cpu1 = _proc_cpu_s(broker.pid)
    except Exception as exc:  # noqa: BLE001 — a red spec must stay parseable
        for child in children:
            if child.poll() is None:
                child.kill()
            child.communicate()  # reap: no zombies/leaked pipe fds
        errors.append(f"{type(exc).__name__}: {exc}")
    finally:
        broker.terminate()
        try:
            broker.wait(timeout=10)
        except subprocess.TimeoutExpired:
            broker.kill()
            broker.wait()
        broker_log.close()
        if store_file:
            try:
                os.unlink(store_file)
            except OSError:
                pass
            # the WAL engine keeps its segments beside the SQLite file
            shutil.rmtree(store_file + ".wal", ignore_errors=True)
    if broker.returncode not in (0, -15):
        errors.append(f"broker rc={broker.returncode}")
    if errors:
        result = {"error": "; ".join(errors)}
        tail = _tail(broker_log.name)
        if tail:
            result["broker_stderr_tail"] = tail[-800:]
        if outputs:  # partial results still help diagnosis
            result["partial_outputs"] = outputs
        try:
            os.unlink(broker_log.name)
        except OSError:
            pass
        return result
    try:
        os.unlink(broker_log.name)
    except OSError:
        pass
    published = sum(o.get("published", 0) for o in outputs)
    delivered = sum(o.get("delivered", 0) for o in outputs)
    p99s = [o["p99_us"] for o in outputs if o.get("p99_us") is not None]
    p50s = [o["p50_us"] for o in outputs if o.get("p50_us") is not None]
    broker_cpu_s = (round(cpu1 - cpu0, 3)
                    if cpu0 is not None and cpu1 is not None else None)
    return {
        "published_per_s": round(published / BENCH_SECONDS, 1),
        "delivered_per_s": round(delivered / BENCH_SECONDS, 1),
        "published": published,
        "delivered": delivered,
        "p50_us": round(max(p50s), 1) if p50s else None,
        "p99_us": round(max(p99s), 1) if p99s else None,
        "wall_s": round(elapsed, 2),
        "broker_cpu_s": broker_cpu_s,
        "cpu_us_per_msg": (round(broker_cpu_s * 1e6 / delivered, 2)
                           if broker_cpu_s is not None and delivered
                           else None),
    }


def _spawn_store_broker(port: int, store_path: str, env: dict, log_file):
    return subprocess.Popen(
        [sys.executable, "-m", "chanamq_tpu.broker.server",
         "--host", "127.0.0.1", "--port", str(port),
         "--no-admin", "--log-level", "WARNING", "--store", store_path],
        env=env, stdout=log_file, stderr=log_file)


def run_wal_recovery_smoke(kill_after_confirms: int = 200,
                           batch: int = 25) -> dict:
    """The kill-9 durability drill: publish persistent messages with
    confirms against a WAL-backed broker subprocess, SIGKILL it mid-stream
    (unconfirmed batch in flight), restart on the same store, drain the
    queue — every confirmed message must come back. The confirmed set is
    exact because a batch only enters it after its last confirm arrived,
    and a WAL confirm means the group commit fsynced it."""
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.client import AMQPClient

    tmpdir = tempfile.mkdtemp(prefix="bench-walrec-")
    store_path = os.path.join(tmpdir, "broker.db")
    port = free_port()
    env = {**os.environ,
           "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))}
    log_file = open(os.path.join(tmpdir, "broker.log"), "ab")
    broker = _spawn_store_broker(port, store_path, env, log_file)
    confirmed: list[bytes] = []
    in_flight = 0
    persistent = BasicProperties(delivery_mode=2)

    async def publish_until_killed() -> None:
        nonlocal in_flight
        conn = await AMQPClient.connect("127.0.0.1", port)
        try:
            ch = await conn.channel()
            await ch.confirm_select()
            await ch.queue_declare("walq", durable=True)
            i = 0
            while i < 100_000:
                bodies = [b"w%06d" % (i + j) for j in range(batch)]
                try:
                    in_flight = len(bodies)
                    for body in bodies:
                        ch.basic_publish(body, routing_key="walq",
                                         properties=persistent)
                    if len(confirmed) >= kill_after_confirms:
                        # the batch above is on the wire, unconfirmed:
                        # the kill lands mid-publish by construction
                        broker.kill()
                    await ch.wait_unconfirmed_below(1, timeout=10)
                except Exception:
                    return  # connection died with the broker
                confirmed.extend(bodies)
                in_flight = 0
                i += batch
        finally:
            try:
                await conn.close()
            except Exception:
                pass

    async def drain() -> set:
        conn = await AMQPClient.connect("127.0.0.1", port)
        try:
            ch = await conn.channel()
            await ch.basic_qos(prefetch_count=PREFETCH)
            got: set = set()
            event = asyncio.Event()

            def on_msg(msg):
                got.add(bytes(msg.body))
                event.set()

            await ch.basic_consume("walq", on_msg, no_ack=True)
            while True:
                event.clear()
                try:
                    await asyncio.wait_for(event.wait(), 2.0)
                except asyncio.TimeoutError:
                    return got
        finally:
            try:
                await conn.close()
            except Exception:
                pass

    t_recover = None
    try:
        wait_port(port)
        asyncio.run(publish_until_killed())
        broker.kill()
        broker.wait()

        t0 = time.perf_counter()
        broker = _spawn_store_broker(port, store_path, env, log_file)
        wait_port(port)
        t_recover = time.perf_counter() - t0
        delivered = asyncio.run(drain())
    finally:
        broker.kill()
        broker.wait()
        log_file.close()
        shutil.rmtree(tmpdir, ignore_errors=True)

    missing = sorted(b.decode() for b in set(confirmed) - delivered)
    return {
        "confirmed": len(confirmed),
        "in_flight_at_kill": in_flight,
        "delivered": len(delivered),
        "lost_confirmed": len(missing),
        "lost_first": missing[:5],
        "recover_s": round(t_recover, 2) if t_recover is not None else None,
    }


async def _start_cluster_node(seeds, store_factory, **cluster_kwargs):
    """Shared bootstrap for the in-process 2-node specs: a BrokerServer on
    an ephemeral port wrapped in a ClusterNode joined to `seeds`. The store
    backend and replication knobs are the only things the specs vary."""
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.cluster.node import ClusterNode

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=store_factory())
    await srv.start()
    cl = ClusterNode(srv.broker, "127.0.0.1", 0, seeds,
                     heartbeat_interval_s=0.2, failure_timeout_s=5,
                     **cluster_kwargs)
    await cl.start()
    return srv, cl


async def _admin_get(port: int, path: str) -> dict:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    return json.loads(raw.partition(b"\r\n\r\n")[2])


async def _admin_text(port: int, path: str) -> str:
    """Like _admin_get but for text/plain payloads (collapsed stacks)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(-1), 10)
    writer.close()
    return raw.partition(b"\r\n\r\n")[2].decode("utf-8", "replace")


async def _trace_gate(admin_port: int, node_names: set) -> dict:
    """BENCH_TRACE=1 smoke gate: scrape /admin/traces and demand at least
    one stitched cross-node trace (>=5 stages spanning >=2 nodes) — the
    whole point of the trailer propagation. Raises to fail the bench."""
    body = await _admin_get(admin_port, "/admin/traces")
    traces = body.get("recent", []) + body.get("slow", [])
    best = None
    for t in traces:
        if len(t.get("nodes", [])) >= 2 and t.get("spans", 0) >= 5:
            if best is None or t["spans"] > best["spans"]:
                best = t
    if best is None:
        raise RuntimeError(
            f"no stitched cross-node trace with >=5 stages among "
            f"{len(traces)} captured (nodes={sorted(node_names)})")
    from urllib.parse import quote

    detail = await _admin_get(
        admin_port, f"/admin/traces/{quote(best['id'], safe='')}")
    return {
        "stitched_id": best["id"],
        "spans": best["spans"],
        "nodes": best["nodes"],
        "total_us": best["total_us"],
        "stages": sorted(detail.get("stages", {})),
        "captured": len(traces),
    }


async def _cluster_spec() -> dict:
    """Two in-process nodes sharing a store: publish a burst via the
    NON-owner (batch-pipelined queue.push_many), then consume remotely
    (per-tick deliver_many events). Evidence for the cluster fast paths;
    in-process, so both nodes share this one core."""
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.sqlite import SqliteStore

    tmpdir = tempfile.mkdtemp(prefix="bench-cluster-")
    store = os.path.join(tmpdir, "shared.db")

    def start_node(seeds):
        return _start_cluster_node(seeds, lambda: SqliteStore(store))

    a_srv = a_cl = b_srv = b_cl = None
    trace_mod = admin = None
    try:
        a_srv, a_cl = await start_node([])
        b_srv, b_cl = await start_node([a_cl.name])
        if os.environ.get("BENCH_TRACE"):
            # trace every publish and expose A's admin API so the tier-1
            # smoke can demand a stitched cross-node trace (both brokers
            # share the one in-process ACTIVE; per-broker trace_node still
            # attributes each span to the right node)
            from chanamq_tpu import trace as trace_mod
            from chanamq_tpu.rest.admin import AdminServer

            trace_mod.install(trace_mod.TraceRuntime(
                sample_rate=1.0, ring_size=1024,
                metrics=a_srv.broker.metrics, node=a_cl.name))
            admin = AdminServer(a_srv.broker, port=0)
            await admin.start()
        for _ in range(100):
            if (len(a_cl.membership.alive_members()) == 2
                    and len(b_cl.membership.alive_members()) == 2):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("2-node membership did not converge")
        qn = next(f"bq{i}" for i in range(200)
                  if a_cl.queue_owner("/", f"bq{i}") == b_cl.name)
        n = 5000
        body = b"x" * BODY_BYTES

        # publish via non-owner A -> owner B, confirmed
        c = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn)
        # the owner's metadata broadcast is fire-and-forget: wait for A to
        # learn the queue exists, else default-exchange publishes racing
        # the replication are silently unroutable
        for _ in range(100):
            if ("/", qn) in a_cl.queue_metas:
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError(f"queue meta for {qn} never replicated")
        t0 = time.perf_counter()
        for _ in range(n):
            ch.basic_publish(body, routing_key=qn)
        await ch.wait_unconfirmed_below(1, timeout=60)
        publish_rate = n / (time.perf_counter() - t0)

        # consume the backlog remotely: owner B -> origin A
        loop = asyncio.get_event_loop()
        got = 0
        done = loop.create_future()
        lat_ns: list = []
        paced_n = 500
        paced_done = loop.create_future()
        phase = {"paced": False}

        def cb(m):
            nonlocal got
            if phase["paced"]:
                lat_ns.append(time.perf_counter_ns() - int(bytes(m.body[:19])))
                if len(lat_ns) >= paced_n and not paced_done.done():
                    paced_done.set_result(None)
                return
            got += 1
            if got >= n and not done.done():
                done.set_result(None)

        t0 = time.perf_counter()
        await ch.basic_consume(qn, cb, no_ack=True)
        await asyncio.wait_for(done, 60)
        consume_rate = n / (time.perf_counter() - t0)

        # paced latency phase: publish -> remote push -> owner dispatch ->
        # remote deliver -> origin render, timed end to end off one clock
        # (both nodes are in-process). ~1k msgs/s, far below saturation, so
        # this measures the interconnect's added latency, not queueing.
        phase["paced"] = True
        stamp_pad = 19  # perf_counter_ns as fixed-width decimal
        for _ in range(paced_n):
            stamp = str(time.perf_counter_ns()).rjust(stamp_pad, "0").encode()
            ch.basic_publish(stamp + body, routing_key=qn)
            await asyncio.sleep(0.001)
        await asyncio.wait_for(paced_done, 60)
        lat_ns.sort()
        await c.close()

        trace_gate = None
        if trace_mod is not None:
            trace_gate = await _trace_gate(admin.bound_port,
                                           {a_cl.name, b_cl.name})

        am, bm = a_srv.broker.metrics, b_srv.broker.metrics
        return {
            **({"trace_gate": trace_gate} if trace_gate is not None else {}),
            "publish_via_nonowner_msgs_per_s": round(publish_rate, 1),
            "remote_consume_msgs_per_s": round(consume_rate, 1),
            "remote_p50_us": round(lat_ns[len(lat_ns) // 2] / 1000, 1),
            "remote_p99_us": round(
                lat_ns[min(len(lat_ns) - 1, int(len(lat_ns) * 0.99))] / 1000, 1),
            "messages": n,
            "interconnect": {
                "push_records": am.rpc_push_records,
                "push_batches": am.rpc_push_batches,
                "deliver_records": bm.rpc_deliver_records,
                "deliver_batches": bm.rpc_deliver_batches,
                "settle_records": am.rpc_settle_records,
                "settle_batches": am.rpc_settle_batches,
                "data_bytes_sent": am.rpc_data_bytes_sent + bm.rpc_data_bytes_sent,
                "data_bytes_recv": am.rpc_data_bytes_recv + bm.rpc_data_bytes_recv,
                "flushes": {
                    "window": am.rpc_flush_window + bm.rpc_flush_window,
                    "bytes": am.rpc_flush_bytes + bm.rpc_flush_bytes,
                    "count": am.rpc_flush_count + bm.rpc_flush_count,
                    "demand": am.rpc_flush_demand + bm.rpc_flush_demand,
                },
            },
        }
    finally:
        if admin is not None:
            try:
                await admin.stop()
            except Exception:
                pass
        if trace_mod is not None:
            trace_mod.clear()
        for part in (b_cl, b_srv, a_cl, a_srv):
            if part is not None:
                try:
                    await part.stop()
                except Exception:
                    pass
        shutil.rmtree(tmpdir, ignore_errors=True)


def run_cluster_spec() -> dict:
    try:
        return asyncio.run(asyncio.wait_for(_cluster_spec(), timeout=120))
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


# ---------------------------------------------------------------------------
# sharded node (chanamq_tpu/shard/): one broker process per core
# ---------------------------------------------------------------------------

SHARD_QUEUE_COUNT = 4
SHARD_PRODUCERS = 3


def _free_port_block(n: int) -> int:
    """Base of `n` consecutive free TCP ports (shard i's listener is
    base + i, so the whole block must be bindable)."""
    for _ in range(64):
        socks: list = []
        try:
            first = socket.socket()
            first.bind(("127.0.0.1", 0))
            base = first.getsockname()[1]
            socks.append(first)
            for i in range(1, n):
                s = socket.socket()
                socks.append(s)
                s.bind(("127.0.0.1", base + i))
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no block of {n} consecutive free ports")


async def _shard_wait_ready(admin_ports: "list[int]", count: int,
                            timeout: float = 30) -> None:
    """Every worker's admin is up and its membership sees all siblings."""
    deadline = time.time() + timeout
    last = "no shard responded yet"
    while time.time() < deadline:
        try:
            if count == 1:
                await _admin_get(admin_ports[0], "/admin/overview")
                return
            converged = 0
            for port in admin_ports:
                body = await _admin_get(port, "/admin/cluster")
                if body.get("enabled") and len(body.get("alive", [])) >= count:
                    converged += 1
            if converged == count:
                return
            last = f"{converged}/{count} shards converged"
        except (OSError, ValueError, asyncio.TimeoutError) as exc:
            last = repr(exc)
        await asyncio.sleep(0.2)
    raise RuntimeError(f"sharded node not ready: {last}")


async def _shard_wait_metas(admin_ports: "list[int]", n_queues: int,
                            timeout: float = 15) -> None:
    """The fire-and-forget metadata broadcast reached every shard — a
    producer whose connection lands on a shard that hasn't heard of
    bench_ex yet would publish unroutably."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            bodies = [await _admin_get(p, "/admin/cluster")
                      for p in admin_ports]
            if all(b.get("known_queues", 0) >= n_queues for b in bodies):
                return
        except (OSError, ValueError, asyncio.TimeoutError):
            pass
        await asyncio.sleep(0.1)
    raise RuntimeError(f"queue metadata did not reach all "
                       f"{len(admin_ports)} shards")


async def _shard_scrape(admin_ports: "list[int]") -> dict:
    """Per-shard broker counters off each worker's /admin/metrics."""
    per_shard = {}
    for i, port in enumerate(admin_ports):
        snap = await _admin_get(port, "/admin/metrics")
        per_shard[str(i)] = {
            "published": snap.get("published_msgs"),
            "delivered": snap.get("delivered_msgs"),
            "delivered_per_s": round(
                (snap.get("delivered_msgs") or 0) / BENCH_SECONDS, 1),
            "cross_pushes": snap.get("shard_cross_pushes"),
            "handoffs": snap.get("shard_handoffs"),
            "restarts": snap.get("shard_restarts"),
        }
    return per_shard


def run_shard_spec(count: int) -> dict:
    """One broker *node* at `count` shards (1 = the unsharded baseline):
    the saturated transient/autoack workload spread over SHARD_QUEUE_COUNT
    queues, then a paced 1p1c latency phase on its own idle queue. The
    node is a single subprocess — past one shard it becomes the
    supervisor and spawns one worker per shard; SO_REUSEPORT spreads the
    client connections, the hash ring spreads queue ownership, and every
    cross-shard message rides the UDS data plane. Per-shard counters come
    from each worker's own admin endpoint (admin base + shard index)."""
    port = free_port()
    admin_base = _free_port_block(count)
    cluster_base = _free_port_block(count)
    shard_dir = tempfile.mkdtemp(prefix="bench-shards-")
    env = {**os.environ,
           "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
           "CHANAMQ_SHARD_COUNT": str(count),
           "CHANAMQ_SHARD_DIR": shard_dir,
           "CHANAMQ_CLUSTER_HOST": "127.0.0.1",
           "CHANAMQ_CLUSTER_PORT": str(cluster_base)}
    broker_log = tempfile.NamedTemporaryFile(
        suffix=".log", prefix="bench-shards-", delete=False)
    broker = subprocess.Popen(
        [sys.executable, "-m", "chanamq_tpu.broker.server",
         "--host", "127.0.0.1", "--port", str(port),
         "--admin-port", str(admin_base), "--log-level", "WARNING"],
        env=env, stdout=broker_log, stderr=broker_log)
    admin_ports = [admin_base + i for i in range(count)]
    keys = [f"bench{i}" for i in range(SHARD_QUEUE_COUNT)]
    queues = [(f"bench_q{i}", [keys[i]]) for i in range(SHARD_QUEUE_COUNT)]
    queues.append(("bench_paced", ["paced"]))
    children: list = []
    errors: list[str] = []
    outputs: list[dict] = []
    paced_outputs: list[dict] = []
    per_shard: dict = {}
    paced_rate = 0
    elapsed = 0.0
    try:
        wait_port(port)
        asyncio.run(_shard_wait_ready(admin_ports, count))
        # declares idempotently retry: right after boot a shard's outbound
        # RPC client to a sibling can still be in reconnect backoff from
        # dialing before that sibling's listener was up, which fails the
        # forwarded remote declare once
        for attempt in range(5):
            try:
                asyncio.run(setup_topology(port, False, "direct", queues))
                break
            except Exception as exc:  # noqa: BLE001
                if attempt == 4:
                    raise RuntimeError(
                        f"topology setup kept failing: {exc!r}") from exc
                time.sleep(0.5)
        if count > 1:
            asyncio.run(_shard_wait_metas(admin_ports, len(queues)))
        # phase 1: saturated transient/autoack across all queues
        for i in range(SHARD_QUEUE_COUNT):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "consumer",
                 "--port", str(port), "--auto-ack", "1",
                 "--seconds", str(BENCH_SECONDS),
                 "--queue", f"bench_q{i}"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        time.sleep(0.3)
        t0 = time.perf_counter()
        for _ in range(SHARD_PRODUCERS):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "producer",
                 "--port", str(port), "--persistent", "0",
                 "--seconds", str(BENCH_SECONDS), "--rate", "0",
                 "--keys", ",".join(keys)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        outs, errs = _reap_children(
            children, SHARD_QUEUE_COUNT, BENCH_SECONDS + 60)
        outputs.extend(outs)
        errors.extend(errs)
        elapsed = time.perf_counter() - t0
        per_shard = asyncio.run(_shard_scrape(admin_ports))
        # phase 2: paced latency on the idle bench_paced queue (its own
        # queue so stale saturated-phase backlog can't pollute the p99),
        # at ~25% of the measured rate — queue delay excluded by design
        delivered_per_s = sum(
            o.get("delivered", 0) for o in outputs) / BENCH_SECONDS
        rate_env = os.environ.get("BENCH_SHARD_PACED_RATE")
        if rate_env is not None:
            paced_rate = int(rate_env)
        else:
            paced_rate = max(500, int(delivered_per_s * 0.25))
        if not errors and delivered_per_s > 0:
            paced_children = [subprocess.Popen(
                [sys.executable, __file__, "--role", "consumer",
                 "--port", str(port), "--auto-ack", "1",
                 "--seconds", str(BENCH_SECONDS),
                 "--queue", "bench_paced"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)]
            time.sleep(0.3)
            paced_children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "producer",
                 "--port", str(port), "--persistent", "0",
                 "--seconds", str(BENCH_SECONDS),
                 "--rate", str(paced_rate), "--keys", "paced"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
            paced_outputs, errs = _reap_children(
                paced_children, 1, BENCH_SECONDS + 60)
            errors.extend(errs)
    except Exception as exc:  # noqa: BLE001 — a red spec must stay parseable
        for child in children:
            if child.poll() is None:
                child.kill()
            child.communicate()  # reap: no zombies/leaked pipe fds
        errors.append(f"{type(exc).__name__}: {exc}")
    finally:
        broker.terminate()
        try:
            # past one shard the node is a supervisor: give it time to
            # SIGTERM and reap every worker before escalating
            broker.wait(timeout=20)
        except subprocess.TimeoutExpired:
            broker.kill()
            broker.wait()
        broker_log.close()
        shutil.rmtree(shard_dir, ignore_errors=True)
    if broker.returncode not in (0, -15):
        errors.append(f"broker rc={broker.returncode}")
    if errors:
        result = {"shards": count, "error": "; ".join(errors)}
        tail = _tail(broker_log.name)
        if tail:
            result["broker_stderr_tail"] = tail[-800:]
        if outputs:
            result["partial_outputs"] = outputs
        try:
            os.unlink(broker_log.name)
        except OSError:
            pass
        return result
    try:
        os.unlink(broker_log.name)
    except OSError:
        pass
    published = sum(o.get("published", 0) for o in outputs)
    delivered = sum(o.get("delivered", 0) for o in outputs)
    p99s = [o["p99_us"] for o in outputs if o.get("p99_us") is not None]
    shard_published = sum(
        s.get("published") or 0 for s in per_shard.values())
    cross_pushes = sum(s.get("cross_pushes") or 0 for s in per_shard.values())
    paced = paced_outputs[0] if paced_outputs else {}
    return {
        "shards": count,
        "published_per_s": round(published / BENCH_SECONDS, 1),
        "delivered_per_s": round(delivered / BENCH_SECONDS, 1),
        "published": published,
        "delivered": delivered,
        "p99_us": round(max(p99s), 1) if p99s else None,
        "per_shard": per_shard,
        "cross_shard_push_ratio": (
            round(cross_pushes / shard_published, 3)
            if count > 1 and shard_published else 0.0),
        "paced_rate": paced_rate,
        "paced_p50_us": paced.get("p50_us"),
        "paced_p99_us": paced.get("p99_us"),
        "wall_s": round(elapsed, 2),
    }


async def _replicate_spec() -> dict:
    """Two in-process nodes with PRIVATE MemoryStores, replicate.factor=2 +
    sync=true: persistent confirmed publishes to the owner, so every confirm
    gates on the follower's replication ack. Measures the price of the
    synchronous durability upgrade (confirm latency) plus the shipping
    pipeline's health (event lag, per-batch ack latency)."""
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.memory import MemoryStore

    persistent = BasicProperties(delivery_mode=2)

    def start_node(seeds):
        return _start_cluster_node(
            seeds, MemoryStore, replicate_factor=2, replicate_sync=True,
            replicate_ack_timeout_ms=2000)

    a_srv = a_cl = b_srv = b_cl = None
    try:
        a_srv, a_cl = await start_node([])
        b_srv, b_cl = await start_node([a_cl.name])
        for _ in range(100):
            if (len(a_cl.membership.alive_members()) == 2
                    and len(b_cl.membership.alive_members()) == 2):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("2-node membership did not converge")
        # a queue OWNED by node A: publishes ride the local fast path and
        # the confirm barrier's replication gate, not a remote push
        qn = next(f"rq{i}" for i in range(200)
                  if a_cl.queue_owner("/", f"rq{i}") == a_cl.name)
        body = b"x" * BODY_BYTES
        c = await AMQPClient.connect("127.0.0.1", a_srv.bound_port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare(qn, durable=True)

        # confirm latency: solo publishes, each awaiting its own confirm
        lat_us = []
        for _ in range(200):
            t0 = time.perf_counter()
            ch.basic_publish(body, routing_key=qn, properties=persistent)
            await ch.wait_unconfirmed_below(1, timeout=10)
            lat_us.append((time.perf_counter() - t0) * 1e6)
        lat_us.sort()

        # throughput: one pipelined confirmed burst
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            ch.basic_publish(body, routing_key=qn, properties=persistent)
        await ch.wait_unconfirmed_below(1, timeout=60)
        rate = n / (time.perf_counter() - t0)
        await c.close()

        repl = a_cl.replication
        snap = a_srv.broker.metrics.snapshot()
        follower_applied = sum(
            copy.applied_seq for copy in b_cl.replication.applier.copies.values())
        return {
            "sync_confirm_p50_us": round(lat_us[len(lat_us) // 2], 1),
            "sync_confirm_p99_us": round(lat_us[int(len(lat_us) * 0.99)], 1),
            "sync_publish_msgs_per_s": round(rate, 1),
            "repl_lag_events": repl.total_lag(),
            "repl_ack_p50_us": snap.get("repl_ack_p50_us"),
            "repl_ack_p99_us": snap.get("repl_ack_p99_us"),
            "events_shipped": snap.get("repl_events_shipped"),
            "batches_shipped": snap.get("repl_batches_shipped"),
            "ack_timeouts": snap.get("repl_ack_timeouts"),
            "follower_applied_seq": follower_applied,
            "messages": n + len(lat_us),
        }
    finally:
        for part in (b_cl, b_srv, a_cl, a_srv):
            if part is not None:
                try:
                    await part.stop()
                except Exception:
                    pass


def run_replicate_spec() -> dict:
    try:
        return asyncio.run(asyncio.wait_for(_replicate_spec(), timeout=120))
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


async def _stream_spec() -> dict:
    """Stream-queue scenario: ONE confirmed producer appends to an
    x-queue-type=stream queue while THREE independent cursors read it —
    attached at "first" (replays the pre-run backlog then follows),
    "next" (tail only) and a mid-run timestamp — every cursor manual-ack
    through prefetch credit. Reports publish throughput plus each
    cursor's committed lag, read off the live queue object."""
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.amqp.value_codec import Timestamp
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.memory import MemoryStore

    qn = "bench_stream"
    warmup = 2000
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=MemoryStore())
    await srv.start()
    conn_p = conn_c = None
    try:
        conn_p = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        pch = await conn_p.channel()
        await pch.confirm_select()
        await pch.queue_declare(qn, durable=True,
                                arguments={"x-queue-type": "stream"})
        props = BasicProperties(delivery_mode=2)
        pad = b"x" * BODY_BYTES

        # pre-run backlog: only the "first" cursor should replay this
        for _ in range(warmup):
            pch.basic_publish(pad, routing_key=qn, properties=props)
        await pch.wait_unconfirmed_below(1, timeout=30)
        attach_ts = Timestamp(int(time.time()))

        conn_c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        counts = {"first": 0, "next": 0, "timestamp": 0}
        channels = {}
        for cursor, offset_spec in (("first", "first"), ("next", "next"),
                                    ("timestamp", attach_ts)):
            ch = await conn_c.channel()
            await ch.basic_qos(prefetch_count=PREFETCH)

            def on_msg(msg, cursor=cursor, ch=ch):
                counts[cursor] += 1
                if counts[cursor] % 500 == 0:
                    ch.basic_ack(msg.delivery_tag, multiple=True)

            await ch.basic_consume(
                qn, on_msg, consumer_tag=f"bench-{cursor}",
                arguments={"x-stream-offset": offset_spec})
            channels[cursor] = ch

        deadline = time.perf_counter() + BENCH_SECONDS
        t0 = time.perf_counter()
        published = 0
        while time.perf_counter() < deadline:
            pch.basic_publish(pad, routing_key=qn, properties=props)
            published += 1
            if len(pch.unconfirmed) >= CONFIRM_WINDOW:
                await conn_p.drain()
                await pch.wait_unconfirmed_below(CONFIRM_WINDOW // 2)
        await conn_p.drain()
        await pch.wait_unconfirmed_below(1, timeout=30)
        publish_rate = published / (time.perf_counter() - t0)

        # drain: every cursor reaches the tail (first also replays warmup)
        targets = {"first": warmup + published, "next": published,
                   "timestamp": published}
        for _ in range(200):
            if all(counts[c] >= targets[c] for c in counts):
                break
            await asyncio.sleep(0.05)
        run_s = time.perf_counter() - t0
        for cursor, ch in channels.items():
            if counts[cursor]:
                ch.basic_ack(0, multiple=True)
        await asyncio.sleep(0.3)  # let the final acks commit cursors

        queue = srv.broker.vhosts["/"].queues[qn]
        lags = {c: queue.cursor_lag(f"bench-{c}") for c in counts}
        snap = srv.broker.metrics.snapshot()
        return {
            "published": published,
            "published_per_s": round(publish_rate, 1),
            "delivered": dict(counts),
            "delivered_per_s_total": round(sum(counts.values()) / run_s, 1),
            "cursor_lag": lags,
            "segments": queue.segment_count,
            "retained_bytes": queue.retained_bytes,
            "stream_cursor_commits": snap.get("stream_cursor_commits"),
        }
    finally:
        for conn in (conn_c, conn_p):
            if conn is not None:
                try:
                    await conn.close()
                except Exception:
                    pass
        await srv.stop()


def run_stream_spec() -> dict:
    try:
        return asyncio.run(asyncio.wait_for(_stream_spec(), timeout=120))
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


# ---------------------------------------------------------------------------
# --route: tensorized router microbench (compile + batch-route vs trie)
# ---------------------------------------------------------------------------

def _route_build_matcher(n: int):
    """Binding corpus at size n: exact-heavy (the shape compiled into the
    host dict) with a capped wildcard tail (the shape the kernel handles),
    mirroring a direct/topic production mix."""
    from chanamq_tpu.broker.matchers import TopicMatcher

    m = TopicMatcher()
    n_wild = min(256, max(16, n // 100))
    for i in range(n - n_wild):
        m.bind(f"t{i % 97}.k{i}.s{i % 31}", f"q{i % 512}")
    for i in range(n_wild):
        pattern = (f"t{i % 97}.*.s{i % 31}" if i % 2
                   else f"w{i % 97}.k{i}.#")
        m.bind(pattern, f"wq{i % 64}")
    return m


def _route_keys(n: int, msgs: int, rng) -> list:
    """Message corpus: drawn from a bounded pool of active routing keys
    (pub/sub traffic reuses keys heavily — topics are stable, messages
    are not), pool mix ~70% exact hits, ~15% wildcard-shaped, ~15%
    misses."""
    pool = []
    pool_size = min(max(msgs // 8, 256), 2048)
    for _ in range(pool_size):
        r = rng.random()
        if r < 0.70:
            i = rng.randrange(n)
            pool.append(f"t{i % 97}.k{i}.s{i % 31}")
        elif r < 0.85:
            i = rng.randrange(max(1, n // 100))
            pool.append(f"t{i % 97}.x{rng.randrange(1000)}.s{i % 31}")
        else:
            pool.append(f"miss.{rng.randrange(10 ** 6)}.z")
    return [rng.choice(pool) for _ in range(msgs)]


def run_route_spec(quick: bool = False) -> dict:
    """Batched tensor routing vs per-message trie walks, single process,
    single core: compile time, µs/msg at each binding-table size, parity
    spot checks, and a 100-group key-shared fan-out through a live broker."""
    import random

    from chanamq_tpu.router.compile import compile_exchange, route_batch

    rng = random.Random(8)
    sizes = [1_000, 10_000] if quick else [1_000, 10_000, 100_000]
    msgs = 2048 if quick else 16384
    batch = 512
    out: dict = {"batch": batch, "msgs": msgs, "sizes": {}}

    for n in sizes:
        m = _route_build_matcher(n)
        t0 = time.perf_counter()
        compiled = compile_exchange("topic", m.bindings())
        compile_s = time.perf_counter() - t0
        keys = _route_keys(n, msgs, rng)
        items = [(k, None) for k in keys]

        t0 = time.perf_counter()
        oracle = [m.route(k) for k in keys]
        trie_s = time.perf_counter() - t0

        uniq_items = [(k, None) for k in dict.fromkeys(keys)]

        backends = {}
        mismatches = 0
        for backend in ("jax", "python"):
            route_batch(compiled, items[:batch], backend)  # warm (jit)
            compiled._route_memo.clear()
            # cold: every key unseen, the all-miss tokenize+kernel path
            t0 = time.perf_counter()
            for i in range(0, len(uniq_items), batch):
                route_batch(compiled, uniq_items[i:i + batch], backend)
            cold_s = time.perf_counter() - t0
            # steady state: bounded active keyset, memo-hit path
            t0 = time.perf_counter()
            got: list = []
            for i in range(0, len(items), batch):
                got.extend(route_batch(compiled, items[i:i + batch],
                                       backend))
            backends[backend] = (cold_s, time.perf_counter() - t0)
            mismatches += sum(
                1 for g, o in zip(got, oracle) if set(g) != o)

        jax_cold, jax_warm = backends["jax"]
        out["sizes"][str(n)] = {
            "bindings": n,
            "kernel_rows": compiled.kernel_rows,
            "unique_keys": len(uniq_items),
            "compile_ms": round(compile_s * 1e3, 2),
            "trie_us_per_msg": round(trie_s / msgs * 1e6, 3),
            "batched_jax_us_per_msg": round(jax_warm / msgs * 1e6, 3),
            "batched_jax_cold_us_per_key": round(
                jax_cold / len(uniq_items) * 1e6, 3),
            "batched_numpy_us_per_msg": round(
                backends["python"][1] / msgs * 1e6, 3),
            "speedup_vs_trie": round(trie_s / jax_warm, 2),
            "parity_mismatches": mismatches,
        }

    if not quick:
        m = _route_build_matcher(1_000_000)
        t0 = time.perf_counter()
        compiled = compile_exchange("topic", m.bindings())
        out["build_1m_bindings_s"] = round(time.perf_counter() - t0, 3)
        out["build_1m_kernel_rows"] = compiled.kernel_rows

    groups = 20 if quick else 100
    records = 100 if quick else 200
    try:
        out["key_shared_fanout"] = asyncio.run(asyncio.wait_for(
            _route_groups_spec(groups, records), timeout=120))
    except Exception as exc:
        out["key_shared_fanout"] = {
            "error": f"{type(exc).__name__}: {exc}"}
    return out


async def _route_groups_spec(groups: int, records: int) -> dict:
    """N key-shared groups fanning one stream out: every group delivers
    every record (group count × record count total deliveries), manual
    ack, 16 partition keys."""
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client.client import AMQPClient

    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
    try:
        setup = await conn.channel()
        await setup.queue_declare(
            "route_ks", durable=True, arguments={"x-queue-type": "stream"})
        await setup.exchange_declare("route_ksx", "fanout")
        await setup.queue_bind("route_ks", "route_ksx", "")

        channels = [await conn.channel() for _ in range(4)]
        total = groups * records
        seen = [0]
        done = asyncio.get_event_loop().create_future()

        def on_msg(ch):
            def cb(msg):
                ch.basic_ack(msg.delivery_tag)
                seen[0] += 1
                if seen[0] >= total and not done.done():
                    done.set_result(None)
            return cb

        for g in range(groups):
            ch = channels[g % len(channels)]
            await ch.basic_consume(
                "route_ks", on_msg(ch), consumer_tag=f"ks-bench-{g}",
                arguments={"x-group": f"g{g}",
                           "x-group-type": "key-shared",
                           "x-stream-offset": "first"})

        t0 = time.perf_counter()
        for i in range(records):
            setup.basic_publish(b"x" * 32, exchange="route_ksx",
                                routing_key=f"k{i % 16}")
        await asyncio.wait_for(done, 90)
        wall = time.perf_counter() - t0
        await asyncio.sleep(0.2)  # let trailing acks commit cursors
        return {
            "groups": groups,
            "records": records,
            "deliveries": total,
            "wall_s": round(wall, 3),
            "deliveries_per_s": round(total / wall, 1),
            "group_cursors_committed": len([
                k for k in srv.broker.vhosts["/"].queues["route_ks"]
                .committed if k.startswith("%grp%")]),
        }
    finally:
        try:
            await conn.close()
        except Exception:
            pass
        await srv.stop()


# ---------------------------------------------------------------------------
# --rpc: request-reply workload (exclusive reply queues, correlation ids)
# ---------------------------------------------------------------------------

async def _rpc_spec(clients: int = 4, servers: int = 2,
                    paced_rate: int = 80) -> dict:
    """Request-reply RPC: N clients each own an exclusive server-named
    reply queue and publish correlated requests to a shared request
    queue; M servers consume it and answer to ``reply_to`` with the
    request's ``correlation_id``. Phase 1 is closed-loop (each client
    pipelines nothing: one request in flight) for round-trips/s; phase 2
    paces each client at a fixed request rate and reports the round-trip
    p50/p99 — the small-message regime the RPCAcc workload targets."""
    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.memory import MemoryStore

    closed_s = max(2.0, min(BENCH_SECONDS, 6.0))
    paced_s = max(2.0, min(BENCH_SECONDS, 4.0))
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=MemoryStore())
    await srv.start()
    conns: list = []
    served = 0
    try:
        boot = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        conns.append(boot)
        bch = await boot.channel()
        await bch.queue_declare("rpc_q")

        for _ in range(servers):
            conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
            conns.append(conn)
            ch = await conn.channel()
            await ch.basic_qos(prefetch_count=64)

            def on_req(msg, ch=ch):
                nonlocal served
                served += 1
                ch.basic_publish(
                    msg.body, routing_key=msg.properties.reply_to,
                    properties=BasicProperties(
                        correlation_id=msg.properties.correlation_id))
                ch.basic_ack(msg.delivery_tag)

            await ch.basic_consume("rpc_q", on_req)

        class RpcClient:
            def __init__(self):
                self.waiting: dict = {}
                self.seq = 0

            async def open(self, idx: int):
                self.idx = idx
                self.conn = await AMQPClient.connect(
                    "127.0.0.1", srv.bound_port)
                conns.append(self.conn)
                self.ch = await self.conn.channel()
                ok = await self.ch.queue_declare("", exclusive=True)
                self.reply_q = ok.queue

                def on_reply(msg):
                    fut = self.waiting.pop(
                        msg.properties.correlation_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(None)

                await self.ch.basic_consume(self.reply_q, on_reply,
                                            no_ack=True)

            async def call(self, body: bytes, timeout: float = 10.0):
                self.seq += 1
                cid = f"c{self.idx}-{self.seq}"
                fut = asyncio.get_event_loop().create_future()
                self.waiting[cid] = fut
                self.ch.basic_publish(
                    body, routing_key="rpc_q",
                    properties=BasicProperties(
                        reply_to=self.reply_q, correlation_id=cid))
                await asyncio.wait_for(fut, timeout)

        rpc_clients = []
        for i in range(clients):
            c = RpcClient()
            await c.open(i)
            rpc_clients.append(c)
        body = b"r" * 64

        # phase 1: closed loop
        async def closed_loop(c) -> int:
            n = 0
            loop = asyncio.get_event_loop()
            end = loop.time() + closed_s
            while loop.time() < end:
                await c.call(body)
                n += 1
            return n

        # clients, servers and broker share this process: the CPU ledger
        # sampled around the closed-loop window is the whole round-trip
        # cost (publish + route + 2x deliver + ack), not broker-only
        cpu0 = _proc_cpu_s(os.getpid())
        t0 = time.perf_counter()
        counts = await asyncio.gather(
            *(closed_loop(c) for c in rpc_clients))
        closed_wall = time.perf_counter() - t0
        cpu1 = _proc_cpu_s(os.getpid())
        round_trips = sum(counts)
        cpu_us_per_msg = (
            round((cpu1 - cpu0) * 1e6 / round_trips, 3)
            if cpu0 is not None and cpu1 is not None and round_trips
            else None)

        # phase 2: paced, round-trip latency under a fixed offered rate
        async def paced_loop(c) -> list:
            lats = []
            loop = asyncio.get_event_loop()
            interval = 1.0 / paced_rate
            end = loop.time() + paced_s
            nxt = loop.time()
            while loop.time() < end:
                nxt += interval
                t = time.perf_counter()
                await c.call(body)
                lats.append((time.perf_counter() - t) * 1e6)
                delay = nxt - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
            return lats

        lat_lists = await asyncio.gather(
            *(paced_loop(c) for c in rpc_clients))
        lats = sorted(x for lst in lat_lists for x in lst)

        def pct(p: float):
            return (round(lats[min(len(lats) - 1,
                                   int(len(lats) * p))], 1)
                    if lats else None)

        return {
            "clients": clients,
            "servers": servers,
            "round_trips": round_trips,
            "round_trips_per_s": round(round_trips / closed_wall, 1),
            "cpu_us_per_msg": cpu_us_per_msg,
            "served": served,
            "paced_rate_per_client": paced_rate,
            "paced_samples": len(lats),
            "paced_p50_us": pct(0.50),
            "paced_p99_us": pct(0.99),
        }
    finally:
        for conn in conns:
            try:
                await conn.close()
            except Exception:
                pass
        await srv.stop()


def run_rpc_spec() -> dict:
    try:
        return asyncio.run(asyncio.wait_for(_rpc_spec(), timeout=120))
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


# ---------------------------------------------------------------------------
# --dlx: dead-letter + priority-queue scenario
# ---------------------------------------------------------------------------

async def _dlx_spec() -> dict:
    """Delivery-semantics scenario: a burst into an x-max-priority queue
    drained in strict priority order (the PriorityFan dispatch path at
    bench scale), then a reject-everything pass through a dead-letter
    exchange asserting exactly-once dead-lettering with x-death headers.
    Reports burst drain throughput and the DLX round-trip rate."""
    import random

    from chanamq_tpu.amqp.properties import BasicProperties
    from chanamq_tpu.broker.server import BrokerServer
    from chanamq_tpu.client import AMQPClient
    from chanamq_tpu.store.memory import MemoryStore

    burst = int(3000 * max(1.0, min(BENCH_SECONDS / 5.0, 4.0)))
    dlx_msgs = 500
    rng = random.Random(17)
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=MemoryStore())
    await srv.start()
    conn = None
    violations: list = []
    try:
        conn = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await conn.channel()
        await ch.exchange_declare("bench_dlx", "fanout")
        await ch.queue_declare("bench_dlq")
        await ch.queue_bind("bench_dlq", "bench_dlx", "")
        await ch.queue_declare("bench_prio", arguments={
            "x-max-priority": 9,
            "x-dead-letter-exchange": "bench_dlx"})

        # phase 1: burst at shuffled priorities, drain in priority order
        # (producer, broker and consumer share this process: the CPU
        # window is the full publish->prio-dispatch->deliver cost)
        cpu0 = _proc_cpu_s(os.getpid())
        t0 = time.perf_counter()
        for i in range(burst):
            ch.basic_publish(
                b"p" * 64, routing_key="bench_prio",
                properties=BasicProperties(priority=rng.randrange(12)))
        drained = 0
        done = asyncio.get_event_loop().create_future()
        last_prio = [9]

        def on_prio(msg):
            nonlocal drained
            drained += 1
            prio = min(msg.properties.priority or 0, 9)
            if prio > last_prio[0]:
                violations.append(
                    f"priority inversion at {drained}: {prio} after "
                    f"{last_prio[0]}")
            last_prio[0] = prio
            if drained >= burst and not done.done():
                done.set_result(None)

        tag = await ch.basic_consume("bench_prio", on_prio, no_ack=True)
        await asyncio.wait_for(done, timeout=60)
        await ch.basic_cancel(tag)
        burst_wall = time.perf_counter() - t0
        cpu1 = _proc_cpu_s(os.getpid())
        cpu_us_per_msg = (
            round((cpu1 - cpu0) * 1e6 / burst, 3)
            if cpu0 is not None and cpu1 is not None and burst else None)

        # phase 2: reject everything once -> exactly-once dead-lettering
        t1 = time.perf_counter()
        for i in range(dlx_msgs):
            ch.basic_publish(b"d%d" % i, routing_key="bench_prio")
        rejected = 0
        rejected_done = asyncio.get_event_loop().create_future()

        def on_reject(msg):
            nonlocal rejected
            rejected += 1
            ch.basic_reject(msg.delivery_tag, requeue=False)
            if rejected >= dlx_msgs and not rejected_done.done():
                rejected_done.set_result(None)

        tag = await ch.basic_consume("bench_prio", on_reject)
        await asyncio.wait_for(rejected_done, timeout=60)
        await ch.basic_cancel(tag)
        seen: dict = {}
        deadline = asyncio.get_event_loop().time() + 10.0
        while (len(seen) < dlx_msgs
               and asyncio.get_event_loop().time() < deadline):
            msg = await ch.basic_get("bench_dlq", no_ack=True)
            if msg is None:
                await asyncio.sleep(0.02)
                continue
            body = bytes(msg.body).decode()
            seen[body] = seen.get(body, 0) + 1
            deaths = (msg.properties.headers or {}).get("x-death") or []
            if (len(deaths) != 1 or deaths[0].get("count") != 1
                    or deaths[0].get("reason") != "rejected"):
                violations.append(f"{body}: bad x-death {deaths}")
        dlx_wall = time.perf_counter() - t1
        if len(seen) != dlx_msgs:
            violations.append(
                f"dead-lettered {len(seen)}/{dlx_msgs} bodies")
        if any(n != 1 for n in seen.values()):
            violations.append("duplicate dead-letters")
        return {
            "burst": burst,
            "burst_drain_per_s": round(burst / burst_wall, 1),
            "cpu_us_per_msg": cpu_us_per_msg,
            "dlx_msgs": dlx_msgs,
            "dlx_round_trip_per_s": round(dlx_msgs / dlx_wall, 1),
            "violations": violations,
        }
    finally:
        if conn is not None:
            try:
                await conn.close()
            except Exception:
                pass
        await srv.stop()


def run_dlx_spec() -> dict:
    try:
        return asyncio.run(asyncio.wait_for(_dlx_spec(), timeout=180))
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


def run_overhead(metric: str, variants: "list[tuple]",
                 budget_pct: "float | None" = None,
                 value_label: "str | None" = None,
                 extra_out: "dict | None" = None) -> None:
    """Shared off-vs-on overhead harness for every observability subsystem
    (--trace-overhead / --telemetry-overhead / --control-overhead /
    --profile-overhead used to carry four copies of this logic).

    `variants` is [(label, extra_env-or-None), ...]; the first is the
    baseline. Reports each variant's throughput delta vs the baseline;
    when `budget_pct` is set (e.g. -2.0), any variant losing more than
    that fails the smoke (exit 1) — tier1.sh retries the whole comparison
    because two independent 5 s runs carry +/-10% noise on a shared box.
    Prints the one-line JSON and exits non-zero on error/over-budget."""
    runs: dict = {}
    for label, extra in variants:
        runs[label] = run_spec("transient_autoack_3p3c", extra_env=extra)
        print(f"# {metric} {label}: {runs[label]}", file=sys.stderr)
    base_label = variants[0][0]
    base = runs[base_label].get("delivered_per_s") or 0
    deltas = {}
    for label, _ in variants[1:]:
        cur = runs[label].get("delivered_per_s")
        deltas[label] = (round((cur - base) / base * 100, 2)
                         if base and cur is not None else None)
    errors = {k: v["error"] for k, v in runs.items() if "error" in v}
    over_budget = budget_pct is not None and any(
        d is not None and d < budget_pct for d in deltas.values())
    value = deltas.get(value_label or variants[1][0])
    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": "%",
        "vs_baseline": None,
        "delta_pct": deltas,
        "delivered_per_s": {
            k: v.get("delivered_per_s") for k, v in runs.items()},
        "cpu_us_per_msg": {
            k: v.get("cpu_us_per_msg") for k, v in runs.items()},
        "body_bytes": BODY_BYTES,
        **({"budget_pct": budget_pct, "within_budget": not over_budget}
           if budget_pct is not None else {}),
        **(extra_out or {}),
        **({"error": errors} if errors else {}),
    }))
    if errors or over_budget:
        sys.exit(1)  # over-budget throughput loss fails the smoke


def run_profile_smoke() -> dict:
    """Attribution smoke: the headline workload against a broker booted
    with the cost ledger + stack sampler on, scraping /admin/profile just
    before and just after the load window. The stage/CPU deltas between
    the two scrapes exclude boot and idle time, so the gate can demand
    that the ledger's non-overlapping top-level windows account for >=90%
    of the broker's measured process CPU, that at least 5 distinct stages
    saw traffic, and that the collapsed-stack endpoint is non-empty."""
    port = free_port()
    admin_port = free_port()
    env = {**os.environ,
           "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
           "CHANAMQ_PROFILE_ENABLED": "true",
           "CHANAMQ_PROFILE_SAMPLE_HZ": "67",
           "CHANAMQ_PROFILE_SLOW_CALLBACK_MS": "250"}
    broker_log = tempfile.NamedTemporaryFile(
        suffix=".log", prefix="bench-profile-", delete=False)
    broker = subprocess.Popen(
        [sys.executable, "-m", "chanamq_tpu.broker.server",
         "--host", "127.0.0.1", "--port", str(port),
         "--admin-port", str(admin_port), "--log-level", "WARNING"],
        env=env, stdout=broker_log, stderr=broker_log)
    children: list = []
    try:
        wait_port(port)
        wait_port(admin_port)
        asyncio.run(setup_topology(port, False))
        snap0 = asyncio.run(_admin_get(admin_port, "/admin/profile"))
        for _ in range(2):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "consumer",
                 "--port", str(port), "--auto-ack", "1",
                 "--seconds", str(BENCH_SECONDS)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        time.sleep(0.3)
        for _ in range(2):
            children.append(subprocess.Popen(
                [sys.executable, __file__, "--role", "producer",
                 "--port", str(port), "--seconds", str(BENCH_SECONDS)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        outputs, errors = _reap_children(children, 2, BENCH_SECONDS + 60)
        snap1 = asyncio.run(_admin_get(admin_port, "/admin/profile"))
        stacks = asyncio.run(_admin_text(
            admin_port, "/admin/profile/stacks"))
    except Exception as exc:  # noqa: BLE001 — a red smoke must stay parseable
        for child in children:
            if child.poll() is None:
                child.kill()
            child.communicate()
        return {"error": f"{type(exc).__name__}: {exc}",
                "broker_stderr_tail": _tail(broker_log.name)[-800:]}
    finally:
        broker.terminate()
        try:
            broker.wait(timeout=10)
        except subprocess.TimeoutExpired:
            broker.kill()
            broker.wait()
        broker_log.close()
        try:
            os.unlink(broker_log.name)
        except OSError:
            pass
    if errors:
        return {"error": "; ".join(errors)}
    delivered = sum(o.get("delivered", 0) for o in outputs)
    stages = {}
    for name, s1 in snap1["stages"].items():
        s0 = snap0["stages"][name]
        d_ns = s1["ns"] - s0["ns"]
        d_calls = s1["calls"] - s0["calls"]
        stages[name] = {
            "ns": d_ns, "calls": d_calls,
            "us_per_call": (round(d_ns / d_calls / 1000.0, 3)
                            if d_calls else None),
        }
    busy_ns = snap1["busy_ns"] - snap0["busy_ns"]
    # the honest denominator is the event-loop thread's CPU (steal-proof,
    # excludes the sampler thread); older payloads only carry process CPU
    loop_cpu_ns = (snap1["loop_cpu_ns"] - snap0["loop_cpu_ns"]
                   if "loop_cpu_ns" in snap1
                   else snap1["process_cpu_ns"] - snap0["process_cpu_ns"])
    active = sorted(n for n, s in stages.items() if s["calls"] > 0)
    stack_lines = [ln for ln in stacks.splitlines() if ln.strip()]
    return {
        "delivered": delivered,
        "delivered_per_s": round(delivered / BENCH_SECONDS, 1),
        "stages": stages,
        "stages_active": active,
        "busy_ns": busy_ns,
        "loop_cpu_ns": loop_cpu_ns,
        "process_cpu_ns": (snap1["process_cpu_ns"]
                           - snap0["process_cpu_ns"]),
        "attributed_pct": (round(busy_ns / loop_cpu_ns * 100, 1)
                           if loop_cpu_ns > 0 else None),
        "gc_pauses": snap1["gc"]["pauses"] - snap0["gc"]["pauses"],
        "samples": (snap1["sampler"]["samples"]
                    - snap0["sampler"]["samples"]),
        "distinct_stacks": snap1["sampler"]["distinct_stacks"],
        "stack_lines": len(stack_lines),
        "slow_callbacks": snap1["slow_callbacks"]["count"],
    }


def main() -> None:
    if "--role" in sys.argv:
        import argparse

        parser = argparse.ArgumentParser()
        parser.add_argument("--role", required=True)
        parser.add_argument("--port", type=int, required=True)
        parser.add_argument("--auto-ack", type=int, default=1)
        parser.add_argument("--persistent", type=int, default=0)
        parser.add_argument("--seconds", type=float, default=5)
        parser.add_argument("--rate", type=int, default=0)
        parser.add_argument("--queue", default="bench_q")
        parser.add_argument("--keys", default="")
        parser.add_argument("--shape", default="burst",
                            choices=("burst", "smooth"))
        args = parser.parse_args()
        if args.role == "producer":
            keys = [k for k in args.keys.split(",") if k] or None
            asyncio.run(producer_main(
                args.port, bool(args.persistent), args.seconds, args.rate,
                keys, args.shape))
        else:
            asyncio.run(consumer_main(
                args.port, bool(args.auto_ack), args.seconds, args.queue))
        return

    if "--route" in sys.argv:
        # tensorized-router microbench: compiled batch routing vs the
        # per-message trie, plus the key-shared group fan-out. --quick
        # shrinks sizes for the tier-1 smoke gate.
        quick = "--quick" in sys.argv
        result = run_route_spec(quick=quick)
        print(f"# route: {result}", file=sys.stderr)
        headline = result["sizes"].get("10000") or next(
            iter(result["sizes"].values()), {})
        parity_bad = sum(s.get("parity_mismatches", 0)
                         for s in result["sizes"].values())
        fanout_err = result.get("key_shared_fanout", {}).get("error")
        print(json.dumps({
            "metric": "route_batched_us_per_msg_10k_bindings",
            "value": headline.get("batched_jax_us_per_msg"),
            "unit": "us/msg",
            "vs_baseline": None,
            "trie_us_per_msg": headline.get("trie_us_per_msg"),
            "speedup_vs_trie": headline.get("speedup_vs_trie"),
            "parity_mismatches": parity_bad,
            "cores": os.cpu_count(),
            "route": result,
        }))
        if parity_bad or fanout_err:
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--stream" in sys.argv:
        # stream-queue scenario only: 1 producer, 3 cursors (first / next /
        # timestamp), manual ack — publish throughput + per-cursor lag
        result = run_stream_spec()
        print(f"# stream_1p3c: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "stream_published_msgs_per_s_1p3cursors",
            "value": result.get("published_per_s"),
            "unit": "msgs/s",
            "vs_baseline": None,
            "delivered_per_s_total": result.get("delivered_per_s_total"),
            "cursor_lag": result.get("cursor_lag"),
            "body_bytes": BODY_BYTES,
            "stream_1p3c": result,
            **({"error": {"stream_1p3c": result["error"]}}
               if "error" in result else {}),
        }))
        return

    if "--rpc" in sys.argv:
        # request-reply workload: 4 clients x 2 servers over exclusive
        # reply queues with correlation-id matching — closed-loop
        # round-trips/s plus a paced round-trip p99
        result = run_rpc_spec()
        print(f"# rpc_4c2s: {result}", file=sys.stderr)
        record = None
        if "error" not in result:
            record = trajectory_record("rpc_4c2s", {
                "delivered_per_s": result.get("round_trips_per_s"),
                "cpu_us_per_msg": result.get("cpu_us_per_msg"),
                "p50_us": result.get("paced_p50_us"),
                "p99_us": result.get("paced_p99_us"),
            })
        if record is not None:
            trajectory_append(record)
        print(json.dumps({
            "metric": "rpc_round_trips_per_s_4c2s",
            "value": result.get("round_trips_per_s"),
            "unit": "round-trips/s",
            "vs_baseline": None,
            "paced_p50_us": result.get("paced_p50_us"),
            "paced_p99_us": result.get("paced_p99_us"),
            "rpc_4c2s": result,
            **({"error": {"rpc_4c2s": result["error"]}}
               if "error" in result else {}),
        }))
        if "error" in result:
            sys.exit(1)
        return

    if "--dlx" in sys.argv:
        # delivery-semantics scenario: priority-fan burst drain in strict
        # priority order, then reject-driven dead-lettering with
        # exactly-once x-death assertions
        result = run_dlx_spec()
        print(f"# dlx_priority: {result}", file=sys.stderr)
        record = None
        if not result.get("error") and not result.get("violations"):
            record = trajectory_record("dlx_priority", {
                "delivered_per_s": result.get("burst_drain_per_s"),
                "cpu_us_per_msg": result.get("cpu_us_per_msg"),
            })
        if record is not None:
            trajectory_append(record)
        print(json.dumps({
            "metric": "dlx_priority_burst_drain_per_s",
            "value": result.get("burst_drain_per_s"),
            "unit": "msgs/s",
            "vs_baseline": None,
            "dlx_round_trip_per_s": result.get("dlx_round_trip_per_s"),
            "violations": result.get("violations"),
            "dlx_priority": result,
            **({"error": {"dlx_priority": result["error"]}}
               if "error" in result else {}),
        }))
        if result.get("error") or result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--semantics-soak" in sys.argv:
        # delivery-semantics chaos soak: seeded kill -9 between Tx.Commit
        # receipt and the WAL group commit (all-or-nothing recovery, no
        # post-rollback ghosts) + TTL-expiry dead-lettering under seeded
        # store faults (exactly-once); both run twice and must be
        # byte-identical per seed
        seed = 42
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        from chanamq_tpu.chaos.soak import run_semantics_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_semantics_soak(seed), timeout=240))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# semantics_soak: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "semantics_soak_violations",
            "value": len(result.get("violations", [])),
            "unit": "violations",
            "vs_baseline": None,
            "seed": seed,
            "deterministic": result.get("deterministic"),
            "semantics_soak": {k: v for k, v in result.items()},
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--semantics-overhead" in sys.argv:
        # master-switch cost: the standard transient scenario with the
        # semantics subsystem disabled (no delay service, no cycle guard,
        # plain deque ready lists) vs the default-on broker; the on-path
        # may cost at most 2%
        run_overhead(
            "semantics_overhead_pct",
            [("off", {"CHANAMQ_SEMANTICS_ENABLED": "false"}), ("on", None)],
            budget_pct=-2.0)
        return

    if "--federation" in sys.argv:
        # two-cluster federation soak: stream segments ship to a mirror
        # cluster, the link is severed mid-stream, the consumer group
        # fails over to the mirror and resumes from its mirrored cursor,
        # the link heals and the backlog drains — zero confirmed loss,
        # contiguous resume, no post-settle duplicates, and a
        # byte-identical same-seed link transition log
        seed = 42
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        from chanamq_tpu.chaos.soak import run_federation_soak

        t0 = time.perf_counter()
        try:
            result = asyncio.run(asyncio.wait_for(
                run_federation_soak(seed), timeout=240))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        elapsed = time.perf_counter() - t0
        print(f"# federation_soak: {result}", file=sys.stderr)
        run = result.get("run") or {}
        # both same-seed runs ship the full stream twice over the link
        shipped = 2 * (run.get("records") or 0)
        print(json.dumps({
            "metric": "federation_soak_violations",
            "value": len(result.get("violations", [])),
            "unit": "violations",
            "vs_baseline": None,
            "seed": seed,
            "deterministic": result.get("deterministic"),
            "mirrored_records_per_s": (
                round(shipped / elapsed, 1) if elapsed > 0 else None),
            "federation_soak": {k: v for k, v in result.items()},
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--federation-overhead" in sys.argv:
        # master-switch cost: federation enabled (listener up, zero links)
        # vs the default-off broker on the standard transient scenario;
        # an idle federation endpoint may cost at most 2%
        run_overhead(
            "federation_overhead_pct",
            [("off", None),
             ("on", {"CHANAMQ_FEDERATION_ENABLED": "true"})],
            budget_pct=-2.0)
        return

    if "--shard" in sys.argv:
        # sharded-node scenario: the saturated transient/autoack workload
        # against a multi-process node at 1/2/4(/N) shards — per-shard and
        # aggregate throughput, the cross-shard UDS push ratio, and a
        # paced p99 at the target count; speedup is always vs the 1-shard
        # run of the same workload
        idx = sys.argv.index("--shard")
        try:
            target = int(sys.argv[idx + 1])
        except (IndexError, ValueError):
            target = 2
        target = max(1, target)
        counts = sorted({1, target} | {c for c in (2, 4) if c < target})
        runs: dict = {}
        for c in counts:
            runs[str(c)] = run_shard_spec(c)
            print(f"# shard_{c}: {runs[str(c)]}", file=sys.stderr)
        base = runs["1"].get("delivered_per_s") or 0
        speedups = {}
        for c in counts[1:]:
            cur = runs[str(c)].get("delivered_per_s")
            speedups[str(c)] = (round(cur / base, 2)
                                if base and cur is not None else None)
        errors = {k: v["error"] for k, v in runs.items() if "error" in v}
        head = runs[str(target)]
        print(json.dumps({
            "metric": f"shard_delivered_msgs_per_s_{target}shards",
            "value": head.get("delivered_per_s"),
            "unit": "msgs/s",
            "vs_baseline": None,
            "speedup_vs_1shard": speedups,
            "cross_shard_push_ratio": head.get("cross_shard_push_ratio"),
            "paced_p99_us": head.get("paced_p99_us"),
            "per_shard": head.get("per_shard"),
            "cores": os.cpu_count(),
            "body_bytes": BODY_BYTES,
            "seconds": BENCH_SECONDS,
            "shard_runs": runs,
            **({"error": errors} if errors else {}),
        }))
        if errors:
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--wal-recovery" in sys.argv:
        # kill-9 durability smoke: any confirmed-message loss exits 1
        result = run_wal_recovery_smoke()
        print(f"# wal_recovery: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "wal_recovery_lost_confirmed",
            "value": result["lost_confirmed"],
            "unit": "messages",
            "vs_baseline": None,
            "wal_recovery": result,
        }))
        if result["lost_confirmed"] or result["confirmed"] == 0:
            sys.exit(1)
        return

    if "--wal" in sys.argv:
        # the WAL delta, measured three ways per ack mode: persistent with
        # the WAL group commit (default), persistent store-direct
        # (CHANAMQ_WAL_ENABLED=false — the pre-WAL baseline), and the
        # matching transient spec the acceptance ratio is taken against;
        # plus the paced persistent p99 with and without the WAL
        direct = {"CHANAMQ_WAL_ENABLED": "false"}
        pairs = {
            "persistent_autoack_3p1c": "transient_autoack_3p1c",
            "persistent_ack_3p1c": "transient_ack_3p1c",
        }
        runs: dict = {}
        ratios: dict = {}
        for name, twin in pairs.items():
            runs[name] = run_spec(name)
            print(f"# {name}: {runs[name]}", file=sys.stderr)
            runs[name + "_store_direct"] = run_spec(name, extra_env=direct)
            print(f"# {name}_store_direct: "
                  f"{runs[name + '_store_direct']}", file=sys.stderr)
            runs[twin] = run_spec(twin)
            print(f"# {twin}: {runs[twin]}", file=sys.stderr)
            got = runs[name].get("delivered_per_s")
            base = runs[twin].get("delivered_per_s")
            ratios[name] = (round(got / base, 3)
                            if got and base else None)
        rate_base = runs["persistent_autoack_3p1c"].get("published_per_s")
        if rate_base:
            rate = max(1000, int(rate_base * 0.25))
            runs[PACED_PERSISTENT_SPEC] = run_spec(
                PACED_PERSISTENT_SPEC, rate=rate)
            runs[PACED_PERSISTENT_SPEC]["rate"] = rate
            runs[PACED_PERSISTENT_SPEC + "_store_direct"] = run_spec(
                PACED_PERSISTENT_SPEC, rate=rate, extra_env=direct)
            runs[PACED_PERSISTENT_SPEC + "_store_direct"]["rate"] = rate
            for label in (PACED_PERSISTENT_SPEC,
                          PACED_PERSISTENT_SPEC + "_store_direct"):
                print(f"# {label}: {runs[label]}", file=sys.stderr)
        errors = {n: r["error"] for n, r in runs.items() if "error" in r}
        print(json.dumps({
            "metric": "wal_persistent_vs_transient_ratio",
            "value": ratios.get("persistent_ack_3p1c"),
            "unit": "ratio",
            "vs_baseline": None,
            "ratios": ratios,
            "paced_persistent_p99_us":
                runs.get(PACED_PERSISTENT_SPEC, {}).get("p99_us"),
            "paced_persistent_p99_us_store_direct":
                runs.get(PACED_PERSISTENT_SPEC + "_store_direct",
                         {}).get("p99_us"),
            "body_bytes": BODY_BYTES,
            "seconds": BENCH_SECONDS,
            "specs": runs,
            **({"error": errors} if errors else {}),
        }))
        if errors:
            sys.exit(1)
        return

    if "--chaos" in sys.argv:
        # seeded chaos soak: the 3-node RF=2 workload of
        # chanamq_tpu/chaos/soak.py under the default fault plan
        # (partition + owner crash + slow store), with every node's store
        # WAL-fronted (CHAOS_WAL=0 reverts to MemoryStore) so confirms
        # gate on the real group-fsync engine. Same seed -> same plan
        # fingerprint and fault schedule; any invariant violation exits
        # non-zero so tier-1 gates on it.
        seed = 42
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        messages = int(os.environ.get("CHAOS_MESSAGES", "160"))
        wal = os.environ.get("CHAOS_WAL", "1") != "0"
        from chanamq_tpu.chaos.soak import run_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_soak(seed, messages=messages, wal=wal), timeout=150))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# chaos_soak: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "chaos_soak_violations",
            "value": len(result.get("violations", [])),
            "unit": "violations",
            "vs_baseline": None,
            "seed": seed,
            "fingerprint": result.get("fingerprint"),
            "confirmed": result.get("confirmed"),
            "duplicates": result.get("duplicates"),
            "promotions": result.get("promotions"),
            "chaos_soak": {k: v for k, v in result.items() if k != "chaos"},
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--elastic" in sys.argv:
        # elasticity chaos soak: 3-node cluster + joiner on private
        # per-node stores (chanamq_tpu/chaos/soak.py run_elastic_soak) —
        # join-triggered rebalance, graceful drain/decommission, kill -9
        # mid-drain, and a healed partition fencing off a stale owner.
        # The episode runs TWICE with the same seed and the normalized
        # decision/evacuation logs must be byte-identical; any invariant
        # violation (confirmed loss, dual holders, unfenced stale ship,
        # non-contiguous stream resume) exits non-zero.
        seed = 11
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        from chanamq_tpu.chaos.soak import run_elastic_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_elastic_soak(seed), timeout=240))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        runs = [{k: v for k, v in run.items() if k != "log_bytes"}
                for run in result.get("runs", [])]
        print(f"# elastic_soak: violations={result.get('violations')} "
              f"log_sha256={result.get('log_sha256')}", file=sys.stderr)
        print(json.dumps({
            "metric": "elastic_soak_violations",
            "value": len(result.get("violations", [])),
            "unit": "violations",
            "vs_baseline": None,
            "seed": seed,
            "log_sha256": result.get("log_sha256"),
            "runs": runs,
            "violations": result.get("violations", []),
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--overload" in sys.argv:
        # overload soak: a deterministic memory-pressure chaos rule drives
        # the flow ladder to the refuse stage under a saturating publisher
        # (chanamq_tpu/chaos/soak.py run_overload_soak). Reports the peak
        # accounted bytes vs the hard limit, paged-body count and the
        # throttle episode latency; any invariant violation (peak over the
        # ceiling, confirmed loss, no refusals, no recovery) exits 1.
        seed = 7
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        messages = int(os.environ.get("OVERLOAD_MESSAGES", "160"))
        from chanamq_tpu.chaos.soak import run_overload_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_overload_soak(seed, messages=messages), timeout=120))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# overload_soak: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "overload_peak_accounted_bytes",
            "value": result.get("peak_accounted_bytes"),
            "unit": "bytes",
            "vs_baseline": None,
            "seed": seed,
            "hard_limit": result.get("hard_limit"),
            "under_hard_limit": bool(result.get("under_hard_limit")),
            "paged_bodies": result.get("paged_bodies"),
            "publishes_refused": result.get("publishes_refused"),
            "throttle_latency_s": result.get("throttle_latency_s"),
            "overload_soak": {k: v for k, v in result.items()
                              if k != "chaos"},
        }))
        if result.get("violations") or not result.get("under_hard_limit"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--control-overhead" in sys.argv:
        # predictive-control cost: the headline transient/autoAck spec
        # with the telemetry stack on, vs the same plus the control plane
        # ticking at 100 ms (10x the default rate). The hot path never
        # sees the control plane — gather is one loop callback, the
        # evaluation runs on its own executor — so the claim is the same
        # <= 2% budget the telemetry sampler is held to.
        base_env = {"CHANAMQ_TELEMETRY_ENABLED": "true",
                    "CHANAMQ_TELEMETRY_INTERVAL": "100ms"}
        run_overhead("control_overhead_pct", [
            ("off", dict(base_env)),
            ("on", {**base_env,
                    "CHANAMQ_CONTROL_ENABLED": "true",
                    "CHANAMQ_CONTROL_INTERVAL": "100ms"}),
        ], budget_pct=-2.0)
        return

    if "--control" in sys.argv:
        # predictive-control spike soak: one seeded burst ramp replayed
        # uncontrolled, controlled (twice, same seed) and dry-run
        # (chanamq_tpu/chaos/soak.py run_control_soak). The controlled
        # runs must peak strictly below the uncontrolled maximum stage
        # with strictly fewer refusals, the same-seed decision logs must
        # compare byte-identical, the dry run must mutate nothing, and
        # no run may lose a confirmed message; any violation exits 1.
        seed = 7
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        from chanamq_tpu.chaos.soak import run_control_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_control_soak(seed), timeout=180))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# control_soak: {result}", file=sys.stderr)
        off = result.get("off") or {}
        on = result.get("on") or {}
        print(json.dumps({
            "metric": "control_spike_stage_delta",
            "value": (off.get("max_stage") - on.get("max_stage")
                      if off.get("max_stage") is not None
                      and on.get("max_stage") is not None else None),
            "unit": "stages",
            "vs_baseline": None,
            "seed": seed,
            "off_max_stage": off.get("max_stage"),
            "on_max_stage": on.get("max_stage"),
            "off_refused": off.get("publishes_refused"),
            "on_refused": on.get("publishes_refused"),
            "off_peak_bytes": off.get("peak_bytes"),
            "on_peak_bytes": on.get("peak_bytes"),
            "decision_log_sha256": on.get("log_sha256"),
            "control_soak": result,
        }))
        if result.get("violations") or not on:
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--churn" in sys.argv:
        # connection-churn leak check: N connect/declare-exclusive/publish/
        # disconnect cycles (half abrupt aborts), then the memory
        # accountant must be back at zero (chanamq_tpu/chaos/soak.py
        # run_connection_churn). Any leaked accounted byte exits 1.
        cycles = int(os.environ.get("CHURN_CYCLES", "500"))
        from chanamq_tpu.chaos.soak import run_connection_churn

        try:
            result = asyncio.run(asyncio.wait_for(
                run_connection_churn(cycles), timeout=180))
        except Exception as exc:
            result = {"cycles": cycles,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# connection_churn: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "churn_leaked_accounted_bytes",
            "value": result.get("leaked_bytes"),
            "unit": "bytes",
            "vs_baseline": None,
            "cycles": result.get("cycles"),
            "aborted": result.get("aborted"),
            "peak_accounted_bytes": result.get("peak_accounted_bytes"),
            "connection_churn": result,
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--cluster" in sys.argv:
        # cluster scenario only: 2 in-process nodes, burst publish via the
        # non-owner + remote consume + paced remote latency — the
        # interconnect fast path as its own BENCH line
        result = run_cluster_spec()
        print(f"# cluster_2node: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "cluster_publish_via_nonowner_msgs_per_s",
            "value": result.get("publish_via_nonowner_msgs_per_s"),
            "unit": "msgs/s",
            "vs_baseline": None,
            "remote_consume_msgs_per_s":
                result.get("remote_consume_msgs_per_s"),
            "remote_p50_us": result.get("remote_p50_us"),
            "remote_p99_us": result.get("remote_p99_us"),
            "body_bytes": BODY_BYTES,
            "cluster_2node": result,
            **({"error": {"cluster_2node": result["error"]}}
               if "error" in result else {}),
        }))
        if "error" in result:
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--trace-overhead" in sys.argv:
        # tracing-cost scenario: the headline transient/autoAck spec run
        # three times — tracing off, the default 1% sample rate, and
        # everything-sampled — reporting the throughput delta vs off.
        # The broker is a subprocess, so tracing is switched via the
        # CHANAMQ_* env overrides it reads at boot. No budget gate: the
        # r1.0 run is expected to cost real throughput.
        run_overhead("trace_overhead_pct_at_r0.01", [
            ("off", None),
            ("r0.01", {"CHANAMQ_TRACE_ENABLED": "true",
                       "CHANAMQ_TRACE_SAMPLE_RATE": "0.01"}),
            ("r1.0", {"CHANAMQ_TRACE_ENABLED": "true",
                      "CHANAMQ_TRACE_SAMPLE_RATE": "1.0"}),
        ], value_label="r0.01")
        return

    if "--otel-overhead" in sys.argv:
        # OTLP-export cost: tracing on at the default 1% sample rate in
        # BOTH variants so the delta isolates what the otel layer adds —
        # the per-publish header probe, the finish-hook enqueue, and the
        # background flusher cycling against a dead collector endpoint
        # (port 1 refuses instantly, so every flush exercises the
        # ReconnectBackoff path, the worst production-adjacent case).
        # Held to the same <= 2% budget as every observability subsystem.
        run_overhead("otel_overhead_pct", [
            ("trace", {"CHANAMQ_TRACE_ENABLED": "true",
                       "CHANAMQ_TRACE_SAMPLE_RATE": "0.01"}),
            ("trace+otel", {"CHANAMQ_TRACE_ENABLED": "true",
                            "CHANAMQ_TRACE_SAMPLE_RATE": "0.01",
                            "CHANAMQ_OTEL_ENABLED": "true",
                            "CHANAMQ_OTEL_ENDPOINT":
                                "http://127.0.0.1:1/v1/traces"}),
        ], budget_pct=-2.0)
        return

    if "--telemetry-overhead" in sys.argv:
        # per-entity sampling cost: the headline transient/autoAck spec
        # with telemetry off vs on at a 100 ms tick (10x the default
        # rate). The hot path only pays the incremental gauge/counter
        # bumps; the sampler walk runs on the timer — the claim is a
        # <= 2% throughput delta, asserted here so tier-1 gates on it.
        run_overhead("telemetry_overhead_pct", [
            ("off", None),
            ("on", {"CHANAMQ_TELEMETRY_ENABLED": "true",
                    "CHANAMQ_TELEMETRY_INTERVAL": "100ms"}),
        ], budget_pct=-2.0)
        return

    if "--profile-overhead" in sys.argv:
        # cost-ledger cost: the headline spec with the profiler off vs on
        # (ledger + watchdog armed, stack sampler off — the production
        # always-on configuration). Every seam accumulates at batch
        # granularity precisely so this delta stays inside the same <= 2%
        # budget the other observability subsystems are held to.
        run_overhead("profile_overhead_pct", [
            ("off", None),
            ("on", {"CHANAMQ_PROFILE_ENABLED": "true",
                    "CHANAMQ_PROFILE_SAMPLE_HZ": "0"}),
        ], budget_pct=-2.0)
        return

    if "--slo-overhead" in sys.argv:
        # SLO-engine cost: telemetry on in BOTH variants (at the same
        # 100 ms tick --telemetry-overhead uses) so the delta isolates
        # what the SLO layer adds per tick — the SLI sampler's counter
        # deltas plus the burn-rate ring update, a few hundred integer
        # ops. Held to the same <= 2% budget as every observability
        # subsystem.
        run_overhead("slo_overhead_pct", [
            ("telemetry", {"CHANAMQ_TELEMETRY_ENABLED": "true",
                           "CHANAMQ_TELEMETRY_INTERVAL": "100ms"}),
            ("telemetry+slo", {"CHANAMQ_TELEMETRY_ENABLED": "true",
                               "CHANAMQ_TELEMETRY_INTERVAL": "100ms",
                               "CHANAMQ_SLO_ENABLED": "true"}),
        ], budget_pct=-2.0)
        return

    if "--event-overhead" in sys.argv:
        # event-bus + firehose cost with nothing bound — the always-on
        # production configuration. Every emit is an O(1) topic-trie
        # miss and a drop-counter bump; every publish/deliver pays one
        # tap call that routes to zero queues. <= 2% budget.
        run_overhead("event_overhead_pct", [
            ("off", None),
            ("on", {"CHANAMQ_EVENTS_ENABLED": "true",
                    "CHANAMQ_FIREHOSE_ENABLED": "true"}),
        ], budget_pct=-2.0)
        return

    if "--tenant" in sys.argv:
        # noisy-neighbor tenancy soak: three tenants on one node
        # (chanamq_tpu/chaos/soak.py run_tenant_soak) — an aggressor
        # floods past its publish-rate token bucket and a memory-share
        # floor pins a backlog tenant, while the victim tenant's paced
        # p99 and tenant-scoped SLO budgets must stay intact and the
        # tenant-filtered event/firehose streams must carry exactly the
        # expected traffic. The episode runs TWICE with the same seed
        # and the tenancy decision logs must be byte-identical; any
        # violation exits non-zero.
        seed = 5
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        from chanamq_tpu.chaos.soak import run_tenant_soak

        try:
            result = asyncio.run(asyncio.wait_for(
                run_tenant_soak(seed), timeout=240))
        except Exception as exc:
            result = {"seed": seed,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# tenant_soak: violations={result.get('violations')} "
              f"log_sha256={result.get('log_sha256')}", file=sys.stderr)
        print(json.dumps({
            "metric": "tenant_soak_violations",
            "value": len(result.get("violations", [])),
            "unit": "violations",
            "vs_baseline": None,
            "seed": seed,
            "log_sha256": result.get("log_sha256"),
            "runs": result.get("runs", []),
            "violations": result.get("violations", []),
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--tenant-churn" in sys.argv:
        # tenant-churn leak check: N define/remove rounds against a live
        # registry, every 100th with a full authenticated AMQP sub-cycle
        # (vhost create / connect / declare / publish-confirmed / delete)
        # — at the end every registry slot, auth view, accounted byte and
        # vhost must be exactly at baseline (chanamq_tpu/chaos/soak.py
        # run_tenant_churn). Any leaked slot or byte exits 1.
        cycles = int(os.environ.get("TENANT_CHURN_CYCLES", "10000"))
        from chanamq_tpu.chaos.soak import run_tenant_churn

        try:
            result = asyncio.run(asyncio.wait_for(
                run_tenant_churn(cycles), timeout=240))
        except Exception as exc:
            result = {"cycles": cycles,
                      "violations": [f"{type(exc).__name__}: {exc}"]}
        print(f"# tenant_churn: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "tenant_churn_leaked_bytes",
            "value": result.get("leaked_bytes"),
            "unit": "bytes",
            "vs_baseline": None,
            "cycles": result.get("cycles"),
            "amqp_cycles": result.get("amqp_cycles"),
            "registry_slots": result.get("registry_slots"),
            "tenant_churn": result,
        }))
        if result.get("violations"):
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--tenant-overhead" in sys.argv:
        # tenancy cost with one quota-less tenant owning "/" — the
        # connection resolves its tenant once at Connection.Open; the
        # publish hot path then pays one attribute load + None check
        # (no rate quota -> no bucket spend) and the delivery path one
        # histogram-presence check. Held to the same <= 2% budget as
        # every other subsystem.
        run_overhead("tenant_overhead_pct", [
            ("off", None),
            ("on", {"CHANAMQ_TENANT_ENABLED": "true",
                    "CHANAMQ_TENANT_TENANTS":
                        '{"t0": {"vhosts": ["/"]}}'}),
        ], budget_pct=-2.0)
        return

    if "--profile" in sys.argv:
        # attribution smoke: ledger + sampler on, /admin/profile scraped
        # around the load window — gates on >=5 stages with traffic,
        # >=90% of broker CPU attributed to the top-level windows, and a
        # non-empty collapsed-stack payload
        result = run_profile_smoke()
        print(f"# profile: {result}", file=sys.stderr)
        active = result.get("stages_active") or []
        attributed = result.get("attributed_pct")
        failures = []
        if "error" in result:
            failures.append(result["error"])
        else:
            if len(active) < 5:
                failures.append(f"only {len(active)} stages saw traffic")
            if attributed is None or attributed < 90.0:
                failures.append(
                    f"attribution {attributed}% below the 90% gate")
            if not result.get("stack_lines"):
                failures.append("empty collapsed-stack payload")
        print(json.dumps({
            "metric": "profile_attributed_cpu_pct",
            "value": attributed,
            "unit": "%",
            "vs_baseline": None,
            "stages_active": active,
            "delivered_per_s": result.get("delivered_per_s"),
            "distinct_stacks": result.get("distinct_stacks"),
            "stack_lines": result.get("stack_lines"),
            "gc_pauses": result.get("gc_pauses"),
            "profile": result,
            **({"error": "; ".join(failures)} if failures else {}),
        }))
        if failures:
            sys.exit(1)  # the tier-1 smoke must fail loudly
        return

    if "--regress" in sys.argv:
        # bench-trajectory regression gate: best-of-N of the headline spec
        # vs the latest comparable line in BENCH_trajectory.jsonl. Never
        # appends unless --record is given (or no baseline exists yet), so
        # two consecutive --regress runs judge against the SAME baseline.
        record = "--record" in sys.argv
        scenario = os.environ.get("BENCH_REGRESS_SPEC",
                                  "transient_autoack_3p3c")
        attempts = max(1, int(os.environ.get("BENCH_REGRESS_RUNS", "2")))
        best = None
        run_errors = []
        for i in range(attempts):
            run = run_spec(scenario)
            print(f"# regress run {i + 1}/{attempts}: {run}",
                  file=sys.stderr)
            if "error" in run:
                run_errors.append(run["error"])
                continue
            rec = trajectory_record(scenario, run)
            if rec is not None and (
                    best is None or rec["us_per_msg"] < best["us_per_msg"]):
                best = rec
        if best is None:
            print(json.dumps({
                "metric": "bench_regress_us_per_msg", "value": None,
                "unit": "us/msg", "vs_baseline": None,
                "scenario": scenario,
                "error": "; ".join(run_errors) or "no clean run"}))
            sys.exit(1)
        traj_stats: dict = {}
        base = trajectory_baseline(scenario, stats=traj_stats)
        corrupt = traj_stats.get("corrupt_lines", 0)
        if corrupt:
            print(f"# regress: skipped {corrupt} corrupt trajectory "
                  f"line(s) in {TRAJECTORY_PATH}", file=sys.stderr)
        if base is None:
            # first run in this environment: seed the trajectory so the
            # next invocation has a baseline — nothing to gate against
            trajectory_append(best)
            print(json.dumps({
                "metric": "bench_regress_us_per_msg",
                "value": best["us_per_msg"],
                "unit": "us/msg", "vs_baseline": None,
                "scenario": scenario, "seeded": True,
                "cpu_us_per_msg": best["cpu_us_per_msg"],
                "trajectory": TRAJECTORY_PATH,
                "corrupt_lines_skipped": corrupt,
            }))
            return
        verdict = regress_evaluate(best, base)
        # the judged-against baseline, stated in full: without the rev +
        # fingerprint a red gate can't be traced back to the run that
        # set the bar
        print(f"# regress baseline: rev={base.get('rev')} "
              f"ts={base.get('ts')} env={base.get('env')} "
              f"us_per_msg={base.get('us_per_msg')} "
              f"cpu_us_per_msg={base.get('cpu_us_per_msg')}",
              file=sys.stderr)
        if record:
            trajectory_append(best)
        print(json.dumps({
            "metric": "bench_regress_us_per_msg",
            "value": best["us_per_msg"],
            "unit": "us/msg",
            "vs_baseline": round(
                (best["us_per_msg"] - base["us_per_msg"])
                / base["us_per_msg"] * 100, 2) if base.get("us_per_msg")
                else None,
            "scenario": scenario,
            "recorded": record,
            "trajectory": TRAJECTORY_PATH,
            "corrupt_lines_skipped": corrupt,
            "base_env": base.get("env"),
            **verdict,
        }))
        if verdict["regressed"]:
            sys.exit(1)  # a confirmed wall+CPU regression fails the gate
        return

    if "--replicate" in sys.argv:
        # replication scenario only: factor-2 sync confirms on private
        # per-node stores (lag + confirm latency as its own BENCH line)
        result = run_replicate_spec()
        print(f"# replicate_2node: {result}", file=sys.stderr)
        print(json.dumps({
            "metric": "replicated_sync_confirm_p99_us",
            "value": result.get("sync_confirm_p99_us"),
            "unit": "us",
            "vs_baseline": None,
            "repl_lag_events": result.get("repl_lag_events"),
            "sync_publish_msgs_per_s":
                result.get("sync_publish_msgs_per_s"),
            "body_bytes": BODY_BYTES,
            "replicate_2node": result,
            **({"error": {"replicate_2node": result["error"]}}
               if "error" in result else {}),
        }))
        return

    which = os.environ.get("BENCH_SPECS", "all")
    if which == "a":
        names = ["transient_autoack_3p3c"]
    elif which == "all":
        names = list(SPECS) + list(TOPO_SPECS)
    else:
        names = [n.strip() for n in which.split(",")
                 if n.strip() in SPECS or n.strip() in TOPO_SPECS]
        if not names:
            print(f"# BENCH_SPECS={which!r} matched no spec; running all",
                  file=sys.stderr)
            names = list(SPECS) + list(TOPO_SPECS)
    results = {}
    for name in names:
        results[name] = run_spec(name)
        print(f"# {name}: {results[name]}", file=sys.stderr)
    headline = results[names[0]]
    paced_shape = "burst"
    if "--paced-shape" in sys.argv:
        paced_shape = sys.argv[sys.argv.index("--paced-shape") + 1]
        if paced_shape not in ("burst", "smooth"):
            print(f"# unknown --paced-shape {paced_shape!r}; using burst",
                  file=sys.stderr)
            paced_shape = "burst"
    if which != "a":
        # paced latency runs at ~25% of the measured PUBLISHED throughput
        # (not delivered: a fan-out headline's delivered rate counts every
        # copy and would oversaturate the 1p1c spec), or the env override.
        # --paced-shape smooth paces per message instead of 10 ms
        # micro-bursts and records under its own scenario name: the burst
        # shape's queueing delay floors the measured p99 near 10 ms, so
        # sub-ms broker latency is only visible in the smooth series.
        for paced_name, env_key, base in (
                (PACED_SPEC, "BENCH_PACED_RATE", headline),
                (PACED_PERSISTENT_SPEC, "BENCH_PACED_PERSISTENT_RATE",
                 results.get("persistent_autoack_3p1c", {}))):
            rate_env = os.environ.get(env_key)
            if rate_env is not None:
                rate = int(rate_env)
            elif base.get("published_per_s"):
                rate = max(1000, int(base["published_per_s"] * 0.25))
            else:
                print(f"# {paced_name}: skipped (no base throughput and "
                      f"no {env_key})", file=sys.stderr)
                continue
            key = (paced_name if paced_shape == "burst"
                   else f"{paced_name}_smooth")
            results[key] = run_spec(paced_name, rate=rate,
                                    shape=paced_shape)
            results[key]["rate"] = rate
            print(f"# {key}: {results[key]}", file=sys.stderr)
    cluster = None
    if which == "all":
        cluster = run_cluster_spec()
        print(f"# cluster_2node: {cluster}", file=sys.stderr)
    # every clean spec run extends the bench trajectory, so the numbers
    # quoted in BENCH.md/README always have a recorded provenance line
    # and `bench.py --regress` has baselines to gate against
    if os.environ.get("BENCH_TRAJECTORY", "1") != "0":
        for name, result in results.items():
            if "error" not in result:
                rec = trajectory_record(name, result)
                if rec is not None:
                    trajectory_append(rec)
    line = {
        "metric": "amqp_delivered_msgs_per_s_transient_autoack_3p3c",
        "value": headline.get("delivered_per_s"),
        "unit": "msgs/s",
        "vs_baseline": None,  # reference published no numbers (BASELINE.md)
        "p99_publish_to_deliver_us": headline.get("p99_us"),
        "paced_p50_us": results.get(PACED_SPEC, {}).get("p50_us"),
        "paced_p99_us": results.get(PACED_SPEC, {}).get("p99_us"),
        "paced_persistent_p99_us":
            results.get(PACED_PERSISTENT_SPEC, {}).get("p99_us"),
        "body_bytes": BODY_BYTES,
        "seconds": BENCH_SECONDS,
        "specs": results,
    }
    if cluster is not None:
        line["cluster_2node"] = cluster
    spec_errors = {n: r["error"] for n, r in results.items() if "error" in r}
    if cluster is not None and "error" in cluster:
        spec_errors["cluster_2node"] = cluster["error"]
    if spec_errors:
        line["error"] = spec_errors
    print(json.dumps(line))


if __name__ == "__main__":
    main()
