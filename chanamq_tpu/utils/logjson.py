"""Structured JSON log output (``chana.mq.log.json``).

One JSON object per line — machine-ingestable without fragile regexes —
stamped with the broker's cluster node id and, when a trace context is
pinned on the running task, the active trace id so log lines can be
joined against ``GET /admin/traces/<id>``.
"""

from __future__ import annotations

import json
import logging


class JsonLogFormatter(logging.Formatter):
    """Render records as single-line JSON objects.

    The node id is read from the broker lazily: ``broker.trace_node``
    starts as ``"local"`` and is updated to ``host:port`` when the
    cluster layer starts, after logging is already configured.
    """

    def __init__(self, broker=None) -> None:
        super().__init__()
        self._broker = broker

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "node": getattr(self._broker, "trace_node", None) or "local",
        }
        # node health verdict on every line: the telemetry service caches
        # a one-word state each sampler tick, so this is an attribute
        # read, never a health evaluation per log record
        svc = getattr(self._broker, "telemetry", None)
        if svc is not None:
            out["health"] = svc.health_state
        from .. import trace

        tid = trace.current_trace_id()
        if tid is not None:
            out["trace"] = tid
            # a propagated W3C context adds the cross-system join key —
            # the same trace_id exported spans and exemplars carry
            w3c = trace.current_w3c_trace_id()
            if w3c is not None:
                out["trace_id"] = w3c
        # structured payloads: callers attach machine-readable fields via
        # `log.warning(..., extra={"data": {...}})` (e.g. the profiler's
        # slow-callback captures ship duration + folded stack this way)
        data = getattr(record, "data", None)
        if isinstance(data, dict):
            out.update(data)
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def install(broker=None) -> None:
    """Swap every root-logger handler's formatter for JSON output."""
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig()
    formatter = JsonLogFormatter(broker)
    for handler in root.handlers:
        handler.setFormatter(formatter)
