"""Node lifecycle tests: graceful drain/decommission, handoff retry and
rollback, holdership fencing epochs, and follower retirement.

The chaos-soak twin (bench.py --elastic) exercises the same machinery at
cluster scale under a seeded fault plan; these tests pin the individual
contracts so a regression is named, not just detected."""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.membership import DRAINING, LEFT
from chanamq_tpu.cluster.node import ClusterNode
from chanamq_tpu.store.memory import MemoryStore
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


class Node:
    def __init__(self, server: BrokerServer, cluster: ClusterNode) -> None:
        self.server = server
        self.cluster = cluster

    @property
    def port(self) -> int:
        return self.server.bound_port

    @property
    def name(self) -> str:
        return self.cluster.name

    @property
    def broker(self) -> Broker:
        return self.server.broker

    async def stop(self) -> None:
        await self.cluster.stop()
        await self.server.stop()


async def start_node(store, seeds, *, replicate_factor=1) -> Node:
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                          store=store)
    await server.start()
    cluster = ClusterNode(server.broker, "127.0.0.1", 0, seeds,
                          heartbeat_interval_s=0.1,
                          failure_timeout_s=0.8,
                          replicate_factor=replicate_factor,
                          replicate_sync=replicate_factor > 1,
                          drain_budget_s=10.0)
    await cluster.start()
    return Node(server, cluster)


async def start_cluster(tmp_path, n=2):
    """n nodes on one shared sqlite store (handoffs rematerialize durable
    content from it, no replication required)."""
    store_path = str(tmp_path / "shared.db")
    first = await start_node(SqliteStore(store_path), [])
    nodes = [first]
    for _ in range(n - 1):
        nodes.append(await start_node(SqliteStore(store_path), [first.name]))
    await converge(nodes, n)
    return nodes


async def converge(nodes, n):
    for _ in range(100):
        if all(len(node.cluster.membership.alive_members()) == n
               for node in nodes):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("membership never converged")


def owned_queue(node, prefix="lq"):
    """A queue name the given node's ring places on itself."""
    return next(f"{prefix}{i}" for i in range(2000)
                if node.cluster.queue_owner("/", f"{prefix}{i}") == node.name)


async def declare_with_backlog(node, qname, count=1):
    client = await AMQPClient.connect("127.0.0.1", node.port)
    ch = await client.channel()
    await ch.confirm_select()
    await ch.queue_declare(qname, durable=True)
    for i in range(count):
        await ch.basic_publish_confirmed(
            b"m%03d" % i, routing_key=qname, properties=PERSISTENT,
            timeout=10)
    await client.close()


async def eventually(predicate, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        if asyncio.get_event_loop().time() > deadline:
            return False
        await asyncio.sleep(0.05)
    return True


# ---------------------------------------------------------------------------
# handoff: activate failure -> bounded retry -> rollback
# ---------------------------------------------------------------------------

async def test_handoff_activate_failure_rolls_back(tmp_path):
    nodes = await start_cluster(tmp_path, 2)
    src, tgt = nodes
    try:
        qname = owned_queue(src)
        await declare_with_backlog(src, qname, 2)
        epoch_before = src.cluster.queue_epoch("/", qname)
        assert epoch_before >= 1  # declare seats the fencing epoch

        async def broken_activate(payload):
            raise OSError("activate refused for the test")

        tgt.cluster.rpc.register("queue.activate", broken_activate)
        ok = await src.cluster.handoff_queue("/", qname, tgt.name)
        assert ok is False
        assert src.broker.metrics.lifecycle_rollbacks == 1
        assert src.broker.metrics.lifecycle_evacuation_retries >= 1
        # the queue is back home with its full backlog...
        queue = src.broker.vhosts["/"].queues[qname]
        assert not queue.deleted and len(queue.messages) == 2
        # ...holdership rolled back to the source with a FRESH epoch, so
        # the aborted target-side claim can never win a late race
        assert src.cluster.queue_metas[("/", qname)]["holder"] == src.name
        assert src.cluster.queue_epoch("/", qname) > epoch_before

        # with the target healthy again the same move goes through
        tgt.cluster.rpc.register("queue.activate",
                                 tgt.cluster._h_queue_activate)
        assert await src.cluster.handoff_queue("/", qname, tgt.name) is True
        assert qname not in src.broker.vhosts["/"].queues
        assert await eventually(
            lambda: qname in tgt.broker.vhosts["/"].queues
            and len(tgt.broker.vhosts["/"].queues[qname].messages) == 2)
    finally:
        for node in nodes:
            await node.stop()


async def test_handoff_target_dies_mid_move(tmp_path):
    nodes = await start_cluster(tmp_path, 3)
    src, tgt, other = nodes
    try:
        qname = owned_queue(src)
        await declare_with_backlog(src, qname, 1)
        # kill the target abruptly: the source still believes it alive, so
        # the handoff proceeds past the holder broadcast and only fails at
        # the activate RPC — the retry loop must give up and roll back
        await tgt.stop()
        ok = await src.cluster.handoff_queue("/", qname, tgt.name)
        assert ok is False
        assert src.broker.metrics.lifecycle_rollbacks == 1
        queue = src.broker.vhosts["/"].queues[qname]
        assert not queue.deleted and len(queue.messages) == 1
        assert src.cluster.queue_metas[("/", qname)]["holder"] == src.name

        # a subsequent drain routes around the corpse onto the live peer
        await eventually(
            lambda: not src.cluster.membership.is_alive(tgt.name))
        src.cluster.lifecycle.drain()
        report = await src.cluster.lifecycle.wait(15)
        assert report["state"] == "drained"
        assert report["failed"] == [] and report["pinned"] == []
        assert await eventually(
            lambda: qname in other.broker.vhosts["/"].queues
            and len(other.broker.vhosts["/"].queues[qname].messages) == 1)
    finally:
        for node in (src, other):
            await node.stop()


# ---------------------------------------------------------------------------
# drain: idempotence, gossip, placement exclusion
# ---------------------------------------------------------------------------

async def test_double_drain_is_idempotent(tmp_path):
    nodes = await start_cluster(tmp_path, 2)
    src, tgt = nodes
    try:
        qname = owned_queue(src)
        await declare_with_backlog(src, qname, 1)
        first = src.cluster.lifecycle.drain()
        second = src.cluster.lifecycle.drain()  # observe, don't restart
        assert first["state"] == second["state"] == "draining"
        assert src.broker.metrics.lifecycle_drains_started == 1
        report = await src.cluster.lifecycle.wait(15)
        assert report["state"] == "drained"
        moved = report["queues_moved"]
        # draining again after completion is a pure observation too
        again = src.cluster.lifecycle.drain()
        assert again["state"] == "drained"
        assert again["queues_moved"] == moved
        assert src.broker.metrics.lifecycle_drains_started == 1
    finally:
        for node in nodes:
            await node.stop()


async def test_drain_gossips_lifecycle_and_leaves_placement(tmp_path):
    nodes = await start_cluster(tmp_path, 2)
    src, peer = nodes
    try:
        qname = owned_queue(src)
        await declare_with_backlog(src, qname, 1)
        assert src.name in peer.cluster.membership.placement_members()
        src.cluster.lifecycle.drain()
        # the evacuation task flips both as its first act
        assert await eventually(
            lambda: src.cluster.draining and src.broker.draining)
        report = await src.cluster.lifecycle.wait(15)
        assert report["state"] == "drained"
        assert report["lifecycle"] == LEFT
        # the terminal state gossips to peers and drops the node from
        # placement while plain liveness still sees the process up
        assert await eventually(
            lambda: peer.cluster.membership.lifecycle_of(src.name) == LEFT)
        assert src.name not in peer.cluster.membership.placement_members()
        assert peer.cluster.membership.is_alive(src.name)
        # anti-entropy must not pull snapshots from the departed member:
        # liveness still says "alive", lifecycle says LEFT, lifecycle wins
        assert src.name not in peer.cluster._anti_entropy_peers()
        assert peer.broker.metrics.lifecycle_left_peer_skipped >= 1
    finally:
        for node in nodes:
            await node.stop()


# ---------------------------------------------------------------------------
# fencing epochs
# ---------------------------------------------------------------------------

async def test_declare_seats_fencing_epoch_on_both_sides():
    a = await start_node(MemoryStore(), [], replicate_factor=2)
    b = await start_node(MemoryStore(), [a.name], replicate_factor=2)
    try:
        await converge([a, b], 2)
        qname = owned_queue(a, "fq")
        await declare_with_backlog(a, qname, 1)
        assert a.cluster.queue_epoch("/", qname) == 1
        assert await eventually(
            lambda: b.cluster.queue_epoch("/", qname) == 1)
    finally:
        await b.stop()
        await a.stop()


async def test_stale_epoch_ship_is_refused():
    a = await start_node(MemoryStore(), [], replicate_factor=2)
    b = await start_node(MemoryStore(), [a.name], replicate_factor=2)
    try:
        await converge([a, b], 2)
        qname = owned_queue(a, "fq")
        await declare_with_backlog(a, qname, 1)
        assert await eventually(
            lambda: b.cluster.replication.applier.copies.get(
                ("/", qname)) is not None)
        # simulate the queue having moved on while A was dark: B knows a
        # newer holdership epoch, so A's next ship arrives stale
        b.cluster.queue_metas[("/", qname)]["epoch"] = 3
        refused_before = b.broker.metrics.lifecycle_stale_epoch_refused
        applied_before = b.cluster.replication.applier.copies[
            ("/", qname)].applied_seq
        client = await AMQPClient.connect("127.0.0.1", a.port)
        ch = await client.channel()
        await ch.confirm_select()
        # the confirm still resolves (the sync barrier gives up on the
        # refusing follower); the invariant is the refusal itself
        await ch.basic_publish_confirmed(
            b"stale", routing_key=qname, properties=PERSISTENT, timeout=10)
        await client.close()
        assert await eventually(
            lambda: b.broker.metrics.lifecycle_stale_epoch_refused
            > refused_before)
        copy = b.cluster.replication.applier.copies.get(("/", qname))
        assert copy is not None and copy.applied_seq == applied_before
    finally:
        await b.stop()
        await a.stop()


async def test_retire_discards_dropped_follower_copy():
    a = await start_node(MemoryStore(), [], replicate_factor=2)
    b = await start_node(MemoryStore(), [a.name], replicate_factor=2)
    try:
        await converge([a, b], 2)
        qname = owned_queue(a, "rq")
        await declare_with_backlog(a, qname, 1)
        applier = b.cluster.replication.applier
        assert await eventually(
            lambda: applier.copies.get(("/", qname)) is not None)
        # wrong owner: the retire must not touch the copy
        reply = await applier.h_retire(
            {"vhost": "/", "queue": qname, "owner": "127.0.0.1:1"})
        assert reply == {"retired": False}
        assert applier.copies.get(("/", qname)) is not None
        # the real owner dropping B from the follower set discards it —
        # a copy that will never see another ship is a split-election
        # seed, not a safety net
        reply = await applier.h_retire(
            {"vhost": "/", "queue": qname, "owner": a.name})
        assert reply == {"retired": True}
        assert applier.copies.get(("/", qname)) is None
    finally:
        await b.stop()
        await a.stop()
