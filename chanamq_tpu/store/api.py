"""Abstract store interface.

Capability parity with the reference's `DBOpService` trait
(chana-mq-server .../store/package.scala:15-43), which exposes ~21 async
operations over messages, queue metas/messages/unacks, exchanges, binds and
vhosts. This interface keeps the same functional surface with an async
Python shape; writes on durable mutations are awaited by the broker before
acknowledging (the reference's Cassandra impl secretly blocked —
CassandraOpService.scala:753-755 — a scar SURVEY.md §7.3 says to avoid).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger("chanamq.store")


def _done_future() -> "asyncio.Future[None]":
    fut: asyncio.Future = asyncio.get_event_loop().create_future()
    fut.set_result(None)
    return fut


# -- replica namespaces (replicate/) ----------------------------------------
# A follower keeps its warm passive copy of a replicated queue under a
# namespaced vhost so the copy shares the blob table / group-commit engine
# with real data but can never collide with it: '\x00' is illegal in AMQP
# short strings, so no client-declared vhost can start with the marker.
# all_queues() excludes replica namespaces — recovery must not resurrect
# passive copies as live queues.

REPLICA_NS = "\x00repl\x00"


def replica_vhost(vhost: str) -> str:
    return REPLICA_NS + vhost


def is_replica_vhost(vhost: str) -> bool:
    return vhost.startswith(REPLICA_NS)


def real_vhost(vhost: str) -> str:
    return vhost[len(REPLICA_NS):] if is_replica_vhost(vhost) else vhost




@dataclass(slots=True)
class StoredMessage:
    id: int
    properties_raw: bytes  # encoded content-header payload (props + body size)
    body: bytes
    exchange: str
    routing_key: str
    refer_count: int
    ttl_ms: Optional[int] = None


@dataclass(slots=True)
class StoredQueue:
    vhost: str
    name: str
    durable: bool = True
    exclusive: bool = False
    auto_delete: bool = False
    ttl_ms: Optional[int] = None
    last_consumed: int = 0
    arguments: dict[str, Any] = field(default_factory=dict)
    # (offset, msg_id, body_size, expire_at_ms|None) of pending messages
    msgs: list[tuple[int, int, int, Optional[int]]] = field(default_factory=list)
    # msg_id -> (offset, body_size, expire_at_ms|None)
    unacks: dict[int, tuple[int, int, Optional[int]]] = field(default_factory=dict)


@dataclass(slots=True)
class StoredExchange:
    vhost: str
    name: str
    type: str
    durable: bool = True
    auto_delete: bool = False
    internal: bool = False
    arguments: dict[str, Any] = field(default_factory=dict)
    # (routing_key, queue, arguments)
    binds: list[tuple[str, str, Optional[dict]]] = field(default_factory=list)
    # exchange-to-exchange bindings: (routing_key, destination, arguments)
    ex_binds: list[tuple[str, str, Optional[dict]]] = field(default_factory=list)


class StoreService:
    """Pluggable durable store. All methods are coroutines."""

    # -- lifecycle --------------------------------------------------------

    async def open(self) -> None: ...

    async def close(self) -> None: ...

    def flush(self, intervals: Optional[list[tuple[int, int]]] = None):
        """Durability barrier: awaitable resolving once every operation
        enqueued so far is committed. Backends that commit synchronously
        (memory) return an immediately-complete awaitable.

        intervals: optional list of (mark_before, mark_after) enqueue
        windows captured via mark(); backends with failure attribution
        (SqliteStore) raise only for failures inside the caller's own
        windows, so one publisher's failed write never errors — or silently
        passes under — another publisher's barrier."""
        return _done_future()

    def mark(self) -> int:
        """Op-sequence watermark for flush(intervals=...). Backends without
        enqueue sequencing return 0 (callers then pass empty/degenerate
        intervals and flush() behaves as a plain barrier)."""
        return 0

    async def approx_data_bytes(self) -> Optional[int]:
        """Approximate live data size of the store, in bytes, for the
        store-growth gate (chana.mq.store.max-bytes): when a paging flood
        is absorbing into the store faster than consumers drain it, the
        broker blocks publishers on this gauge the same way it does on
        resident RAM. None = backend cannot report (gate inert)."""
        return None

    # -- fire-and-forget fast paths ----------------------------------------
    # The per-message hot ops (message blob, queue-log row, unack rows) are
    # written fire-and-forget: callers need program-order enqueueing and
    # barrier coverage, not a per-op completion handle. Backends override
    # these to skip the future machinery (SqliteStore enqueues a bare
    # callable; MemoryStore applies eagerly); the defaults wrap the async
    # variant in a logged task so any backend is correct out of the box.

    # background write failures feed telemetry's store-error window and
    # the readiness gate; always present so health code reads it directly
    error_count: int = 0

    def _fire(self, aw) -> None:
        """Track a fire-and-forget store write: kept alive in a per-store
        set (an un-referenced task may be GC'd before running), failures
        logged, drained by drain_nowait() at shutdown. This is THE
        fire-and-forget tracker — Broker.store_bg routes here too."""
        tasks = getattr(self, "_fired_tasks", None)
        if tasks is None:
            tasks = self._fired_tasks = set()
        task = asyncio.ensure_future(aw)
        tasks.add(task)
        task.add_done_callback(self._fire_done)

    def _fire_done(self, task) -> None:
        self._fired_tasks.discard(task)
        if not task.cancelled() and task.exception():
            # error_count feeds the health readiness check (telemetry/):
            # a store that is failing background writes is not ready
            self.error_count = getattr(self, "error_count", 0) + 1
            log.error("background store write failed: %r", task.exception())

    async def drain_nowait(self) -> None:
        """Let tracked fire-and-forget writes land — call before close().
        (Backends overriding every *_nowait op may have nothing here; the
        built-ins apply/enqueue at call time and flush in close().)"""
        tasks = getattr(self, "_fired_tasks", None)
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    def insert_message_nowait(self, msg: StoredMessage) -> None:
        self._fire(self.insert_message(msg))

    def insert_queue_msg_nowait(
        self, vhost: str, queue: str, offset: int, msg_id: int,
        body_size: int, expire_at_ms: Optional[int],
    ) -> None:
        self._fire(self.insert_queue_msg(
            vhost, queue, offset, msg_id, body_size, expire_at_ms))

    def insert_queue_unacks_nowait(
        self, vhost: str, queue: str,
        unacks: list[tuple[int, int, int, Optional[int]]],
    ) -> None:
        self._fire(self.insert_queue_unacks(vhost, queue, unacks))

    # -- messages (refcounted blobs; reference: insertMessage/selectMessage/
    #    deleteMessage + referMessage/unreferMessage) ----------------------

    async def insert_message(self, msg: StoredMessage) -> None:
        raise NotImplementedError

    async def select_message(self, msg_id: int) -> Optional[StoredMessage]:
        raise NotImplementedError

    async def select_messages(self, msg_ids: list[int]) -> dict[int, StoredMessage]:
        """Batch form of select_message (hot on the hydration path —
        reattaching passivated bodies at a queue head). Missing ids are
        simply absent from the result."""
        out: dict[int, StoredMessage] = {}
        for msg_id in msg_ids:
            msg = await self.select_message(msg_id)
            if msg is not None:
                out[msg_id] = msg
        return out

    async def select_message_metas(self, msg_ids: list[int]) -> dict[int, StoredMessage]:
        """Batch metadata read: like select_messages but bodies are omitted
        (body=None) — recovery uses it to rebuild deep backlogs without
        holding every blob in RAM. The default strips bodies after a full
        read so every backend keeps the contract; backends that can skip
        the body column entirely (SqliteStore) override it and also avoid
        the blob I/O."""
        full = await self.select_messages(msg_ids)
        # strip into fresh copies: select_messages makes no promise that
        # the returned objects aren't the backend's own cached instances,
        # so mutating them in place could corrupt the store
        return {
            mid: dataclasses.replace(meta, body=None)  # type: ignore[arg-type]
            for mid, meta in full.items()
        }

    async def delete_message(self, msg_id: int) -> None:
        raise NotImplementedError

    async def delete_messages(self, msg_ids: list[int]) -> None:
        """Batch form of delete_message (hot on the ack path)."""
        for msg_id in msg_ids:
            await self.delete_message(msg_id)

    async def update_message_refer_count(self, msg_id: int, count: int) -> None:
        raise NotImplementedError

    # -- queue meta (reference: insertQueueMeta/selectQueueMeta/deleteQueueMeta)

    async def insert_queue_meta(self, q: StoredQueue) -> None:
        raise NotImplementedError

    async def select_queue(self, vhost: str, name: str) -> Optional[StoredQueue]:
        """Reconstruct meta + pending msgs + unacks (reference: selectQueue)."""
        raise NotImplementedError

    async def all_queues(self, vhost: Optional[str] = None) -> list[StoredQueue]:
        """Every stored queue, EXCLUDING replica namespaces (passive copies
        must never recover as live queues)."""
        raise NotImplementedError

    # -- queue message log (reference: insertQueueMsg/deleteQueueMsg) ------

    async def insert_queue_msg(
        self, vhost: str, queue: str, offset: int, msg_id: int,
        body_size: int, expire_at_ms: Optional[int],
    ) -> None:
        raise NotImplementedError

    async def delete_queue_msg(self, vhost: str, queue: str, offset: int) -> None:
        raise NotImplementedError

    async def iter_queue_msgs(
        self, vhost: str, queue: str, after_offset: int, limit: int
    ) -> list[tuple[int, int, int, Optional[int]]]:
        """Page through a queue's pending log rows in offset order:
        up to `limit` rows with offset > after_offset, as
        (offset, msg_id, body_size, expire_at_ms). Replication resync uses
        this to stream the owner's snapshot in bounded chunks. The default
        rides select_queue; SqliteStore overrides with a ranged query."""
        sq = await self.select_queue(vhost, queue)
        if sq is None:
            return []
        rows = sorted(m for m in sq.msgs if m[0] > after_offset)
        return rows[:limit]

    async def replace_queue_msgs(
        self, vhost: str, queue: str,
        msgs: list[tuple[int, int, int, Optional[int]]],
    ) -> None:
        """Swap a queue's pending log rows wholesale (replication resync
        installs the owner's snapshot; promotion materializes a passive
        copy). msgs: (offset, msg_id, body_size, expire_at_ms)."""
        await self.purge_queue_msgs(vhost, queue)
        for offset, msg_id, body_size, expire_at_ms in msgs:
            await self.insert_queue_msg(
                vhost, queue, offset, msg_id, body_size, expire_at_ms)

    async def replace_queue_unacks(
        self, vhost: str, queue: str,
        unacks: list[tuple[int, int, int, Optional[int]]],
    ) -> None:
        """Swap a queue's unack rows wholesale (companion of
        replace_queue_msgs). unacks: (msg_id, offset, body_size,
        expire_at_ms)."""
        existing = await self.select_queue(vhost, queue)
        if existing and existing.unacks:
            await self.delete_queue_unacks(
                vhost, queue, list(existing.unacks))
        if unacks:
            await self.insert_queue_unacks(vhost, queue, unacks)

    # -- consumption watermark + unacks (reference: updateQueueLastConsumed,
    #    insertQueueUnack/deleteQueueUnack) --------------------------------

    async def update_queue_last_consumed(
        self, vhost: str, queue: str, last_consumed: int
    ) -> None:
        raise NotImplementedError

    async def insert_queue_unacks(
        self, vhost: str, queue: str,
        unacks: list[tuple[int, int, int, Optional[int]]],
    ) -> None:
        """unacks: (msg_id, offset, body_size, expire_at_ms|None)."""
        raise NotImplementedError

    async def delete_queue_msgs_offsets(
        self, vhost: str, queue: str, offsets: list[int]
    ) -> None:
        """Remove specific queue-log rows by offset. Priority queues settle
        per-row (consumption is not in offset order, so the lastConsumed
        watermark cannot prune for them)."""
        raise NotImplementedError

    async def delete_queue_unacks(
        self, vhost: str, queue: str, msg_ids: list[int]
    ) -> None:
        raise NotImplementedError

    # -- queue delete with archival (reference: pendingDeleteQueue copies
    #    rows into *_deleted tables before deleting, then forceDeleteQueue)

    async def archive_queue(self, vhost: str, queue: str) -> None:
        raise NotImplementedError

    async def delete_queue(self, vhost: str, queue: str) -> None:
        raise NotImplementedError

    async def purge_queue_msgs(self, vhost: str, queue: str) -> None:
        raise NotImplementedError

    # -- stream segments + cursors (streams/: no reference analogue — the
    #    reference has no log queues). Sealed segments persist as one blob
    #    row each; cursors are the server-tracked committed offsets, keyed
    #    by consumer tag, that let reconnecting stream readers resume. ----

    async def insert_stream_segment(
        self, vhost: str, queue: str, base_offset: int, last_offset: int,
        first_ts_ms: int, last_ts_ms: int, size_bytes: int, blob: bytes,
    ) -> None:
        raise NotImplementedError

    async def select_stream_segment(
        self, vhost: str, queue: str, base_offset: int
    ) -> Optional[bytes]:
        raise NotImplementedError

    async def stream_segment_metas(
        self, vhost: str, queue: str
    ) -> list[tuple[int, int, int, int, int]]:
        """Segment index in base-offset order, blobs omitted:
        (base_offset, last_offset, first_ts_ms, last_ts_ms, size_bytes).
        Recovery rebuilds the in-memory log from this alone."""
        raise NotImplementedError

    async def delete_stream_segments(
        self, vhost: str, queue: str, base_offsets: list[int]
    ) -> None:
        """Whole-segment truncation (retention / purge)."""
        raise NotImplementedError

    async def update_stream_cursor(
        self, vhost: str, queue: str, name: str, committed_offset: int
    ) -> None:
        raise NotImplementedError

    async def select_stream_cursors(
        self, vhost: str, queue: str
    ) -> dict[str, int]:
        """cursor name -> committed offset."""
        raise NotImplementedError

    async def delete_stream_data(self, vhost: str, queue: str) -> None:
        """Drop ALL of a stream's segments and cursors (queue delete)."""
        raise NotImplementedError

    # -- exchanges + binds (reference: insertExchange/selectExchange/
    #    deleteExchange, insertExchangeBind/deleteExchangeBind) ------------

    async def insert_exchange(self, ex: StoredExchange) -> None:
        raise NotImplementedError

    async def select_exchange(self, vhost: str, name: str) -> Optional[StoredExchange]:
        raise NotImplementedError

    async def all_exchanges(self, vhost: Optional[str] = None) -> list[StoredExchange]:
        raise NotImplementedError

    async def delete_exchange(self, vhost: str, name: str) -> None:
        raise NotImplementedError

    async def insert_bind(
        self, vhost: str, exchange: str, queue: str, routing_key: str,
        arguments: Optional[dict],
    ) -> None:
        raise NotImplementedError

    async def delete_bind(
        self, vhost: str, exchange: str, queue: str, routing_key: str
    ) -> None:
        raise NotImplementedError

    async def delete_queue_binds(self, vhost: str, queue: str) -> None:
        raise NotImplementedError

    # -- exchange-to-exchange binds (no reference analogue: the reference
    #    stubs Exchange.Bind/Unbind, FrameStage.scala:1023-1027) -----------

    async def insert_exchange_bind(
        self, vhost: str, source: str, destination: str, routing_key: str,
        arguments: Optional[dict],
    ) -> None:
        raise NotImplementedError

    async def delete_exchange_bind(
        self, vhost: str, source: str, destination: str, routing_key: str
    ) -> None:
        raise NotImplementedError

    async def delete_exchange_binds_dest(
        self, vhost: str, destination: str
    ) -> None:
        """Remove every e2e bind targeting a deleted destination exchange."""
        raise NotImplementedError

    # -- cluster worker-id allocation (reference: GlobalNodeIdService hands
    #    out monotonically increasing ids; here the shared store is the
    #    durable counter so ids never repeat across leader failovers) ------

    async def allocate_worker_id(self) -> int:
        raise NotImplementedError

    # -- vhosts (reference: insertVhost/selectAllVhosts/deleteVhost) -------

    async def insert_vhost(self, name: str, active: bool = True) -> None:
        raise NotImplementedError

    async def all_vhosts(self) -> list[tuple[str, bool]]:
        raise NotImplementedError

    async def delete_vhost(self, name: str) -> None:
        raise NotImplementedError
