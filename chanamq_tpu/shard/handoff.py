"""fd-handoff fallback for platforms without SO_REUSEPORT.

The supervisor runs the one TCP listener (:class:`HandoffAcceptor`) and
ships each accepted client socket to a worker over that worker's
``handoff-<i>.sock`` feed using SCM_RIGHTS (``socket.send_fds``),
round-robin. The worker (:class:`HandoffReceiver`) adopts the
descriptor into its own event loop and hands the resulting stream pair
to the ordinary ``BrokerServer._on_client`` — above the accept, the
two listener modes are indistinguishable.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
from typing import Optional

log = logging.getLogger("chanamq.shard.handoff")

_MAX_FDS_PER_MSG = 8


class HandoffReceiver:
    """Worker side: adopt client sockets pushed over the feed socket."""

    def __init__(self, server, path: str) -> None:
        self.server = server  # BrokerServer
        self.path = path
        self._listener: Optional[socket.socket] = None
        self._feeds: list[socket.socket] = []
        self._accept_task: Optional[asyncio.Task] = None
        self.adopted = 0

    async def start(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.path)
        listener.listen(4)
        listener.setblocking(False)
        self._listener = listener
        self._accept_task = asyncio.get_event_loop().create_task(
            self._accept_loop())
        log.info("fd-handoff feed listening at %s", self.path)

    async def _accept_loop(self) -> None:
        loop = asyncio.get_event_loop()
        assert self._listener is not None
        try:
            while True:
                feed, _addr = await loop.sock_accept(self._listener)
                feed.setblocking(False)
                self._feeds.append(feed)
                loop.add_reader(feed.fileno(), self._on_feed_readable, feed)
        except (asyncio.CancelledError, OSError):
            pass

    def _on_feed_readable(self, feed: socket.socket) -> None:
        loop = asyncio.get_event_loop()
        try:
            msg, fds, _flags, _addr = socket.recv_fds(
                feed, 64, _MAX_FDS_PER_MSG)
        except BlockingIOError:
            return
        except OSError:
            msg, fds = b"", []
        if not msg and not fds:
            # supervisor went away: drop this feed (a respawned
            # supervisor reconnects)
            try:
                loop.remove_reader(feed.fileno())
            except (OSError, ValueError):
                pass
            if feed in self._feeds:
                self._feeds.remove(feed)
            feed.close()
            return
        for fd in fds:
            self._adopt(fd)

    def _adopt(self, fd: int) -> None:
        loop = asyncio.get_event_loop()
        sock = socket.socket(fileno=fd)
        sock.setblocking(False)
        self.adopted += 1
        reader = asyncio.StreamReader(loop=loop)

        def _connected(r: asyncio.StreamReader,
                       w: asyncio.StreamWriter) -> None:
            loop.create_task(self.server._on_client(r, w))

        protocol = asyncio.StreamReaderProtocol(reader, _connected, loop=loop)

        async def _attach() -> None:
            try:
                await loop.connect_accepted_socket(lambda: protocol, sock)
            except OSError as exc:
                log.warning("adopting handed-off fd failed: %r", exc)
                sock.close()

        loop.create_task(_attach())

    async def stop(self) -> None:
        if self._accept_task is not None:
            self._accept_task.cancel()
            self._accept_task = None
        loop = asyncio.get_event_loop()
        for feed in self._feeds:
            try:
                loop.remove_reader(feed.fileno())
            except (OSError, ValueError):
                pass
            feed.close()
        self._feeds.clear()
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


class HandoffAcceptor:
    """Supervisor side: the single TCP accept loop."""

    def __init__(self, host: str, port: int, worker_paths: list[str],
                 *, backlog: int = 128) -> None:
        self.host = host
        self.port = port
        self.worker_paths = list(worker_paths)
        self.backlog = backlog
        self._server: Optional[asyncio.AbstractServer] = None
        self._feeds: dict[str, socket.socket] = {}
        self._next = 0
        self.dispatched = 0
        self.dropped = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port, backlog=self.backlog)
        log.info("handoff acceptor on %s:%d -> %d workers",
                 self.host, self.port, len(self.worker_paths))

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    def _feed(self, path: str) -> socket.socket:
        feed = self._feeds.get(path)
        if feed is None:
            feed = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            feed.connect(path)  # local, small: blocking connect is fine
            self._feeds[path] = feed
        return feed

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is None:
            writer.close()
            return
        fd = sock.fileno()
        # round-robin with failover: a worker mid-restart is skipped
        for attempt in range(len(self.worker_paths)):
            path = self.worker_paths[self._next % len(self.worker_paths)]
            self._next += 1
            try:
                socket.send_fds(self._feed(path), [b"c"], [fd])
            except OSError:
                stale = self._feeds.pop(path, None)
                if stale is not None:
                    stale.close()
                continue
            self.dispatched += 1
            break
        else:
            self.dropped += 1
            log.warning("no worker reachable; dropping client")
        # SCM_RIGHTS duplicated the descriptor into the worker (or the
        # client is being refused): the local copy closes either way
        writer.close()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for feed in self._feeds.values():
            feed.close()
        self._feeds.clear()
