"""Localhost admin REST API.

Capability parity with the reference's AdminApi
(chana-mq-server .../rest/AdminApi.scala:20-61: GET /admin/vhost/put/{v} and
/admin/vhost/delete/{v}, bound to localhost, with access logging), extended
with the observability endpoints the reference lacked (SURVEY.md §5):
metrics snapshot, overview, and per-queue stats.

Hand-rolled HTTP/1.1 on asyncio (no third-party web framework in the image).
Reads are GET with JSON responses (plus the text-format Prometheus scrape at
/metrics); vhost mutations require POST.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional
from urllib.parse import parse_qs, unquote

from ..broker.broker import Broker
from ..store.api import is_replica_vhost

log = logging.getLogger("chanamq.admin")


class AdminError(Exception):
    """An expected, client-facing request failure: carries the HTTP status
    and a stable message. Anything else that escapes a handler is an
    internal error — logged with traceback server-side, reported to the
    client as an opaque 500 (raw exception text leaks paths, queue names
    and implementation detail to anything that can reach the port)."""

    def __init__(self, status: str, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


class _Response:
    """Handler return wrapper for non-200 success-path statuses (the
    readiness probe answers 503 with a perfectly well-formed body)."""

    __slots__ = ("status", "payload")

    def __init__(self, status: str, payload: object) -> None:
        self.status = status
        self.payload = payload


class AdminServer:
    def __init__(
        self, broker: Broker, host: str = "127.0.0.1", port: int = 15672
    ) -> None:
        self.broker = broker
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        log.info("admin API on http://%s:%d/admin", self.host, self.port)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            # drain headers, keeping Content-Length so POST bodies (the
            # /admin/chaos/install plan JSON) can be read
            content_length = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                if name.strip().lower() == "content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        pass
            body = b""
            if content_length > 0:
                # 1 MiB cap: admin bodies are small JSON documents
                body = await asyncio.wait_for(
                    reader.readexactly(min(content_length, 1 << 20)), 10)
            status, payload = await self._route(method, path, body)
            if isinstance(payload, str):
                # pre-rendered text body (Prometheus exposition format)
                body = payload.encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = json.dumps(payload, default=str).encode()
                ctype = "application/json"
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body
            )
            await writer.drain()
            log.info("%s %s -> %s", method, path, status.split()[0])
        except (asyncio.TimeoutError, ConnectionResetError):
            pass
        except Exception:
            log.exception("admin request failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _route(
        self, method: str, path: str, body: bytes = b""
    ) -> tuple[str, object]:
        path, _, qs = path.partition("?")
        query = {k: v[-1] for k, v in parse_qs(qs).items()}
        segments = [unquote(s) for s in path.strip("/").split("/") if s]
        matched = self._match(segments, body, query)
        if matched is None:
            # unknown path: 404 regardless of verb
            return "404 Not Found", {"error": "unknown path"}
        allowed, handler = matched
        if isinstance(allowed, dict):
            # verb-dispatched path (GET /admin/drain observes, POST starts)
            handler = allowed.get(method)
            if handler is None:
                return ("405 Method Not Allowed",
                        {"error": f"use {' or '.join(sorted(allowed))}"})
        elif method != allowed:
            # KNOWN path, wrong verb: 405 naming the verb that works —
            # never the blanket 404 that made a POSTed scrape or a GET
            # mutation attempt indistinguishable from a typo'd path
            return "405 Method Not Allowed", {"error": f"use {allowed}"}
        try:
            result = handler()
            if asyncio.iscoroutine(result):
                result = await result
            if isinstance(result, _Response):
                return result.status, result.payload
            return "200 OK", result
        except AdminError as exc:
            return exc.status, {"error": exc.message}
        except Exception:
            # stable opaque shape to the client, full traceback in the log
            log.exception("admin handler failed: %s %s", allowed,
                          "/" + "/".join(segments))
            return "500 Internal Server Error", {"error": "internal error"}

    def _match(self, segments: list, body: bytes = b"", query: dict = None):
        """Resolve a path to (allowed_method, handler) or None. Handlers
        may be sync or async; mutations require POST (a GET mutation is
        CSRF-triggerable from any web page even on localhost), reads GET.
        Paths mirror the reference's AdminApi plus the observability
        endpoints it lacked."""
        query = query or {}
        if segments == ["metrics"]:
            # conventional Prometheus scrape path (text exposition format);
            # ?format=openmetrics upgrades to OpenMetrics with exemplars
            return ("GET", lambda: self._prometheus(query))
        if not segments or segments[0] != "admin":
            return None
        rest = segments[1:]
        if len(rest) == 3 and rest[0] == "vhost":
            name = rest[2]
            if rest[1] == "put":
                return ("POST", lambda: self._vhost_put(name))
            if rest[1] == "delete":
                return ("POST", lambda: self._vhost_delete(name))
            return None
        if rest == ["metrics"]:
            return ("GET", self.broker.metrics_snapshot)
        if rest == ["overview"]:
            return ("GET", self._overview)
        if len(rest) == 2 and rest[0] == "queues":
            return ("GET", lambda: self._queues(rest[1]))
        if len(rest) == 2 and rest[0] == "exchanges":
            return ("GET", lambda: self._exchanges(rest[1]))
        if rest == ["streams"]:
            return ("GET", self._streams)
        if rest == ["cluster"]:
            return ("GET", self._cluster)
        if rest == ["drain"]:
            return ({"POST": self._drain_start,
                     "GET": self._drain_status}, None)
        if rest == ["replication"]:
            return ("GET", self._replication)
        if rest == ["forecast"]:
            return ("GET", self._forecast)
        if rest == ["control"]:
            return ("GET", lambda: self._control(query))
        if rest == ["control", "configure"]:
            return ("POST", lambda: self._control_configure(body))
        if rest == ["chaos"]:
            return ("GET", self._chaos_status)
        if rest == ["chaos", "install"]:
            return ("POST", lambda: self._chaos_install(body))
        if rest == ["chaos", "clear"]:
            return ("POST", self._chaos_clear)
        if rest == ["traces"]:
            return ("GET", lambda: self._traces(query))
        if len(rest) == 2 and rest[0] == "traces":
            return ("GET", lambda: self._trace_detail(rest[1]))
        if rest == ["otel", "spans"]:
            return ("GET", lambda: self._otel_spans(query))
        if rest == ["timeseries"]:
            return ("GET", lambda: self._timeseries(query))
        if len(rest) == 4 and rest[:2] == ["timeseries", "queue"]:
            return ("GET", lambda: self._timeseries_queue(
                rest[2], rest[3], query))
        if len(rest) == 3 and rest[:2] == ["timeseries", "connection"]:
            return ("GET", lambda: self._timeseries_conn(rest[2], query))
        if rest == ["profile"]:
            return ("GET", self._profile)
        if rest == ["profile", "stacks"]:
            return ("GET", self._profile_stacks)
        if len(rest) == 3 and rest[:2] == ["profile", "stage"]:
            return ("GET", lambda: self._profile_stage(rest[2]))
        if rest == ["health"]:
            return ("GET", lambda: self._health(query))
        if rest == ["health", "live"]:
            return ("GET", lambda: {"live": True})
        if rest == ["alerts"]:
            return ("GET", lambda: self._alerts(query))
        if rest == ["slo"]:
            return ("GET", lambda: self._slo(query))
        if rest == ["slo", "configure"]:
            return ("POST", lambda: self._slo_configure(body))
        if rest == ["events"]:
            return ("GET", self._events_status)
        if rest == ["federation"]:
            return ({"GET": self._federation,
                     "POST": lambda: self._federation_post(body)}, None)
        if rest == ["tenants"]:
            return ({"GET": self._tenants,
                     "POST": lambda: self._tenant_put(body)}, None)
        if len(rest) == 2 and rest[0] == "tenants":
            return ("GET", lambda: self._tenant_detail(rest[1]))
        if len(rest) == 3 and rest[0] == "tenants" and rest[2] == "delete":
            return ("POST", lambda: self._tenant_delete(rest[1]))
        return None

    @staticmethod
    def _q_int(query: dict, key: str, default: int, lo: int, hi: int) -> int:
        try:
            return max(lo, min(int(query.get(key, default)), hi))
        except (TypeError, ValueError):
            raise AdminError("400 Bad Request",
                             f"query parameter {key!r} must be an integer")

    # -- per-entity telemetry (chanamq_tpu/telemetry/) ----------------------

    def _svc(self):
        svc = getattr(self.broker, "telemetry", None)
        if svc is None:
            raise AdminError(
                "409 Conflict",
                "telemetry disabled: boot with chana.mq.telemetry.enabled")
        return svc

    async def _timeseries(self, query: dict) -> dict:
        """Cluster-wide per-entity series: every alive node's payload plus
        a merged top-K-by-rate summary. ?window=N ticks, ?top=K queues per
        node (0 = all), ?scope=local skips the peer pull."""
        svc = self._svc()
        window = self._q_int(query, "window", 60, 1, 4096)
        top = self._q_int(query, "top", 0, 0, 1024)
        if query.get("scope") == "local":
            nodes = {self.broker.trace_node: svc.local_payload(window, top)}
            out = {"nodes": nodes, "origin": self.broker.trace_node}
        else:
            out = await svc.cluster_payload(window, top)
        out["top_queues"] = self._merge_top(
            out["nodes"], top or 8)
        return out

    @staticmethod
    def _merge_top(nodes: dict, k: int) -> list:
        """Cluster-wide top-K queues by publish+deliver rate, from the
        newest vector of each queue series in each node payload."""
        rows = []
        for node, payload in nodes.items():
            fields = payload.get("fields", {}).get("queue")
            if not fields:
                continue  # peer errored or telemetry disabled there
            for entry in payload.get("queues", []):
                series = entry.get("series") or []
                if not series:
                    continue
                latest = dict(zip(fields, series[-1]))
                rate = (latest.get("publish_rate", 0.0)
                        + latest.get("deliver_rate", 0.0))
                rows.append({"node": node, "vhost": entry["vhost"],
                             "name": entry["name"], "rate": rate, **latest})
        rows.sort(key=lambda r: (-r["rate"], r["node"], r["vhost"], r["name"]))
        return rows[:k]

    async def _timeseries_queue(
        self, vhost: str, name: str, query: dict
    ) -> dict:
        """Single-queue drilldown; searches peers when the queue is not
        sampled locally (it lives on its owner node)."""
        svc = self._svc()
        window = self._q_int(query, "window", 120, 1, 4096)
        series = svc.queues.series((vhost, name), window)
        if series is not None:
            return {"node": self.broker.trace_node, "vhost": vhost,
                    "name": name, "fields": list(svc.queues.fields),
                    "series": series.tolist()}
        payload = await svc.cluster_payload(window)
        for node, node_payload in payload["nodes"].items():
            for entry in node_payload.get("queues", []):
                if entry["vhost"] == vhost and entry["name"] == name:
                    return {"node": node, "vhost": vhost, "name": name,
                            "fields": node_payload["fields"]["queue"],
                            "series": entry["series"]}
        raise AdminError("404 Not Found",
                         f"no telemetry for queue {vhost}/{name}")

    async def _timeseries_conn(self, conn_id: str, query: dict) -> dict:
        svc = self._svc()
        window = self._q_int(query, "window", 120, 1, 4096)
        try:
            key = int(conn_id)
        except ValueError:
            raise AdminError("400 Bad Request", "connection id must be an integer")
        series = svc.conns.series(key, window)
        if series is not None:
            return {"node": self.broker.trace_node, "id": key,
                    "fields": list(svc.conns.fields),
                    "series": series.tolist()}
        payload = await svc.cluster_payload(window)
        for node, node_payload in payload["nodes"].items():
            for entry in node_payload.get("connections", []):
                if entry["id"] == key:
                    return {"node": node, "id": key,
                            "fields": node_payload["fields"]["connection"],
                            "series": entry["series"]}
        raise AdminError("404 Not Found", f"no telemetry for connection {key}")

    async def _health(self, query: dict):
        """Readiness probe: 200 when ready, 503 with reasons when not —
        pointable straight at a load balancer. Works without telemetry
        (drain, shard, and memory-pressure checks only); ?scope=cluster
        adds every peer's verdict."""
        svc = getattr(self.broker, "telemetry", None)
        if svc is not None:
            out = svc.health()
        else:
            from ..telemetry.health import flow_check, shard_check

            draining = bool(getattr(self.broker, "draining", False))
            reasons = (["draining: shutdown in progress"]
                       if draining else [])
            checks: dict = {"draining": {"ok": not draining}}
            # shard-sibling liveness and the overload ladder need no
            # telemetry, only membership / the accountant
            shards = shard_check(self.broker)
            if shards is not None:
                checks["shards"], shard_reasons = shards
                reasons.extend(shard_reasons)
            pressure = flow_check(self.broker)
            if pressure is not None:
                checks["memory_pressure"], flow_reasons = pressure
                reasons.extend(flow_reasons)
            out = {"node": self.broker.trace_node, "live": True,
                   "ready": not reasons, "reasons": reasons,
                   "checks": checks}
        if query.get("scope") == "cluster" and svc is not None:
            payload = await svc.cluster_payload(1)
            out["cluster"] = {
                node: node_payload.get(
                    "health", {"error": node_payload.get("error", "no data")})
                for node, node_payload in payload["nodes"].items()
            }
        if not out["ready"]:
            return _Response("503 Service Unavailable", out)
        return out

    async def _alerts(self, query: dict) -> dict:
        """Alert rules + firing state, cluster-wide by default (every
        node evaluates its own entities; the union is the operator's
        pager view). ?scope=local skips the peer pull."""
        svc = self._svc()
        out = {"node": self.broker.trace_node, **svc.engine.snapshot()}
        if query.get("scope") != "local":
            payload = await svc.cluster_payload(1)
            out["cluster"] = {}
            for node, node_payload in payload["nodes"].items():
                alerts = node_payload.get("alerts")
                if alerts is None:
                    out["cluster"][node] = {
                        "error": node_payload.get("error", "no data")}
                else:
                    out["cluster"][node] = {
                        "firing": alerts["firing"],
                        "fired_total": alerts["fired_total"],
                        "resolved_total": alerts["resolved_total"],
                        "fired_rules": alerts["fired_rules"],
                    }
        return out

    # -- SLOs and the event bus (chanamq_tpu/slo/, chanamq_tpu/events/) ----

    def _slo_engine(self):
        svc = self._svc()
        if svc.slo is None:
            raise AdminError(
                "409 Conflict",
                "slo disabled: boot with chana.mq.slo.enabled or POST "
                "/admin/slo/configure")
        return svc, svc.slo

    async def _slo(self, query: dict) -> dict:
        """SLO specs, burn rates, error budgets and firing pairs —
        cluster-aggregated by default (each node evaluates its own SLIs;
        the pager view wants every node's budget plus the cluster's
        worst case). ?scope=local skips the peer pull."""
        _, engine = self._slo_engine()
        out = {"node": self.broker.trace_node, **engine.snapshot()}
        if query.get("scope") == "local":
            return out
        me = self.broker.trace_node

        def _summary(snap: dict) -> dict:
            return {
                "firing": snap.get("firing", []),
                "fired_total": snap.get("fired_total", 0),
                "budget": {s["name"]: s["budget_remaining"]
                           for s in snap.get("slos", [])},
            }

        out["cluster"] = {me: _summary(out)}
        cluster = self.broker.cluster
        if cluster is not None and cluster.membership is not None:
            for peer in cluster.membership.alive_members():
                if peer == cluster.name:
                    continue
                try:
                    snap = await cluster._call(
                        peer, "slo.pull", {}, timeout_s=2.0)
                except Exception as exc:
                    out["cluster"][peer] = {
                        "error": f"pull failed: {type(exc).__name__}"}
                    continue
                if "error" in snap:
                    out["cluster"][peer] = {"error": snap["error"]}
                else:
                    out["cluster"][peer] = _summary(snap)
        # the cluster-level answer: per SLO, the worst remaining budget
        # across nodes (one node burning is the on-call's problem)
        worst: dict = {}
        for entry in out["cluster"].values():
            for name, remaining in (entry.get("budget") or {}).items():
                worst[name] = min(worst.get(name, 1.0), remaining)
        out["budget_worst_case"] = worst
        return out

    def _slo_configure(self, body: bytes) -> dict:
        """Replace the SLO spec set at runtime. Budgets and burn windows
        reset with the specs (they are properties of the objective, not
        of the process). Installs onto a telemetry service booted without
        SLOs too — the next tick starts evaluating."""
        from ..slo import (
            SLOEngine, attach_tenant_latency, default_slos, specs_from_json,
        )

        svc = self._svc()
        try:
            req = json.loads(body or b"{}")
        except ValueError as exc:
            raise AdminError("400 Bad Request", f"bad json: {exc}")
        raw = req.get("specs") if isinstance(req, dict) else req
        try:
            if raw:
                engine = SLOEngine(specs_from_json(raw, svc.interval_s))
            else:
                engine = SLOEngine(default_slos(svc.interval_s))
        except ValueError as exc:
            raise AdminError("400 Bad Request", str(exc))
        svc.set_slo(engine)
        attach_tenant_latency(engine, self.broker.tenancy)
        return {"ok": True,
                "slos": [spec.name for spec in engine.specs]}

    # -- multi-tenancy (chanamq_tpu/tenancy/) -------------------------------

    def _tenancy(self):
        registry = self.broker.tenancy
        if registry is None:
            raise AdminError(
                "409 Conflict",
                "tenancy disabled: boot with chana.mq.tenant.enabled")
        return registry

    def _tenants(self) -> dict:
        """Registry snapshot: every tenant's quotas, live resource counts,
        token-bucket level and gate state."""
        return self._tenancy().snapshot()

    def _tenant_put(self, body: bytes) -> dict:
        """Define (or replace) one tenant at runtime. Body is the same
        spec shape chana.mq.tenant.tenants takes, plus a "name" key:
        {"name": "...", "vhosts": [...], "users": {...}, "acls": {...},
        "quota": {...}}. New users/ACLs apply from the next handshake."""
        from ..tenancy import TenancyError

        registry = self._tenancy()
        try:
            req = json.loads(body or b"{}")
        except ValueError as exc:
            raise AdminError("400 Bad Request", f"bad json: {exc}")
        if not isinstance(req, dict) or not isinstance(req.get("name"), str) \
                or not req["name"]:
            raise AdminError("400 Bad Request",
                             'body must be an object with a "name" string')
        spec = {k: v for k, v in req.items() if k != "name"}
        try:
            tenant = registry.define(req["name"], spec)
        except TenancyError as exc:
            raise AdminError("400 Bad Request", str(exc))
        return {"ok": True, "tenant": tenant.snapshot()}

    def _tenant_detail(self, name: str) -> dict:
        registry = self._tenancy()
        tenant = registry.tenants.get(name)
        if tenant is None:
            raise AdminError("404 Not Found", f"unknown tenant {name!r}")
        return tenant.snapshot()

    def _tenant_delete(self, name: str) -> dict:
        """Remove a tenant: gates lift, connections detach (and stay open
        — removal revokes quotas, not sessions), vhosts/users return to
        the global namespace."""
        registry = self._tenancy()
        if not registry.remove(name):
            raise AdminError("404 Not Found", f"unknown tenant {name!r}")
        return {"ok": True, "tenant": name}

    def _events_status(self) -> dict:
        """Event-bus + firehose status: installed?, exchanges, publish /
        drop counters (the operator's 'is anything listening?' check)."""
        from .. import events as events_mod

        bus = events_mod.ACTIVE
        fh = events_mod.FIREHOSE
        m = self.broker.metrics
        out: dict = {
            "enabled": bus is not None,
            "firehose_enabled": fh is not None,
            "events": {
                "published": m.events_published_total,
                "dropped": m.events_dropped_total,
            },
            "firehose": {
                "published": m.firehose_published_total,
                "dropped": m.firehose_dropped_total,
            },
        }
        if bus is not None:
            out["bus"] = bus.snapshot()
        if fh is not None:
            out["firehose"].update({
                "vhost": fh.vhost, "queue_filter": fh.queue_filter})
        return out

    # -- federation (chanamq_tpu/federation/) ------------------------------

    def _federation_svc(self):
        svc = getattr(self.broker, "federation", None)
        if svc is None:
            raise AdminError(
                "409 Conflict",
                "federation disabled: boot with chana.mq.federation.enabled")
        return svc

    def _federation(self) -> dict:
        """Per-link state, lag, outbox depth and the recent event log."""
        return self._federation_svc().stats()

    def _federation_post(self, body: bytes) -> dict:
        """Operator nudges: {"action": "wake"[, "link": name]} forces an
        immediate pump instead of waiting out the idle tick (the runbook's
        first move after healing a severed link)."""
        svc = self._federation_svc()
        try:
            req = json.loads(body or b"{}")
        except ValueError as exc:
            raise AdminError("400 Bad Request", f"bad json: {exc}")
        action = req.get("action")
        if action != "wake":
            raise AdminError("400 Bad Request",
                             'supported actions: "wake"')
        target = req.get("link")
        woke = []
        for link in svc.links:
            if target is None or link.name == target:
                link.wake()
                woke.append(link.name)
        if target is not None and not woke:
            raise AdminError("404 Not Found", f"no link {target!r}")
        return {"ok": True, "woke": woke}

    # -- message tracing (chanamq_tpu/trace/) ------------------------------

    # dimension filters understood by /admin/traces; values match the
    # attrs the publish path stamps on every sampled/forced trace
    _TRACE_FILTERS = ("queue", "exchange", "vhost", "tenant", "stage")

    def _traces(self, query: dict = None) -> dict:
        from .. import trace

        query = query or {}
        runtime = trace.ACTIVE
        out = {
            "enabled": bool(getattr(self.broker, "trace_enabled", False)),
            "installed": runtime is not None,
        }
        if runtime is not None:
            filters = {k: query[k] for k in self._TRACE_FILTERS
                       if k in query}
            if filters or "min_duration_us" in query or "format" in query:
                limit = self._q_int(query, "limit", 50, 1, 512)
                min_us = self._q_int(query, "min_duration_us", 0,
                                     0, 2 ** 31)
                matched = runtime.query(limit=limit,
                                        min_duration_us=min_us, **filters)
                if query.get("format") == "otlp":
                    from ..otel.export import (default_resource,
                                               resource_spans)

                    return resource_spans(
                        matched, default_resource(self.broker))
                out["matched"] = len(matched)
                out["traces"] = [t.to_dict() for t in matched]
                return out
            out.update(runtime.status())
            stage_hs = self.broker.metrics.trace_stage_us
            out["stage_latency_us"] = {
                key: {
                    "count": h.count,
                    "p50": h.percentile_us(0.50),
                    "p99": h.percentile_us(0.99),
                    "mean": h.mean_us,
                }
                for key, h in stage_hs.items()
            }
        return out

    def _trace_detail(self, trace_id: str) -> dict:
        from .. import trace

        runtime = trace.ACTIVE
        if runtime is None:
            raise AdminError("409 Conflict", "tracing not installed")
        found = runtime.find(trace_id)
        if found is None:
            raise AdminError("404 Not Found",
                             f"no trace {trace_id!r} in the rings")
        out = found.to_dict()
        out["finished"] = found.finished
        return out

    def _otel_spans(self, query: dict) -> dict:
        """Pull-mode OTLP export: drains the exporter's pending queue
        when the push exporter is installed (so a collector-less deploy
        can still scrape spans), otherwise renders the completed rings
        through the same OTLP shaper."""
        from .. import trace

        runtime = trace.ACTIVE
        if runtime is None:
            raise AdminError("409 Conflict", "tracing not installed")
        limit = self._q_int(query, "limit", 64, 1, 1024)
        otel = getattr(self.broker, "otel", None)
        if otel is not None:
            return otel.pull(limit)
        from ..otel.export import default_resource, resource_spans

        return resource_spans(runtime.query(limit=limit),
                              default_resource(self.broker))

    # -- fault injection (chanamq_tpu/chaos/) ------------------------------

    def _chaos_status(self) -> dict:
        from .. import chaos

        runtime = chaos.ACTIVE
        out = {
            "enabled": bool(getattr(self.broker, "chaos_enabled", False)),
            "installed": runtime is not None,
        }
        if runtime is not None:
            out.update(runtime.status())
        return out

    def _chaos_install(self, body: bytes) -> dict:
        from .. import chaos

        if not getattr(self.broker, "chaos_enabled", False):
            raise AdminError(
                "409 Conflict",
                "chaos disabled: boot with chana.mq.chaos.enabled")
        try:
            plan = chaos.FaultPlan.from_dict(json.loads(body or b"{}"))
        except (ValueError, KeyError, TypeError) as exc:
            raise AdminError("400 Bad Request", f"bad plan: {exc}")
        chaos.install(plan, metrics=self.broker.metrics)
        return {
            "ok": True,
            "seed": plan.seed,
            "rules": [r.name for r in plan.rules],
            "fingerprint": plan.fingerprint(),
        }

    def _chaos_clear(self) -> dict:
        from .. import chaos

        fires = chaos.ACTIVE.plan.total_fires if chaos.ACTIVE else 0
        chaos.clear()
        return {"ok": True, "total_fires": fires}

    async def _vhost_put(self, name: str) -> dict:
        await self.broker.create_vhost(name)
        return {"ok": True, "vhost": name}

    async def _vhost_delete(self, name: str) -> dict:
        deleted = await self.broker.delete_vhost(name)
        return {"ok": deleted, "vhost": name}

    def _forecast(self):
        forecaster = getattr(self.broker, "forecaster", None)
        if forecaster is None:
            return {"enabled": False}
        return forecaster.snapshot()

    def _control(self, query: dict):
        control = getattr(self.broker, "control", None)
        if control is None:
            return {"enabled": False}
        tail = self._q_int(query, "log", 32, 0, 4096)
        return control.snapshot(tail=tail)

    def _control_configure(self, body: bytes) -> dict:
        """Runtime knobs for the rollout path: observe decisions with
        {"dry-run": true} (the boot default), then lift it without a
        restart once the log looks right."""
        control = getattr(self.broker, "control", None)
        if control is None:
            raise AdminError(
                "409 Conflict",
                "control disabled: boot with chana.mq.control.enabled")
        try:
            req = json.loads(body or b"{}")
        except ValueError as exc:
            raise AdminError("400 Bad Request", f"bad json: {exc}")
        if not isinstance(req, dict):
            raise AdminError("400 Bad Request", "body must be an object")
        if "dry-run" in req:
            control.dry_run = bool(req["dry-run"])
        for feature in ("admission", "rebalance", "prefetch"):
            if feature in req:
                setattr(control, f"{feature}_enabled", bool(req[feature]))
        return {"ok": True, "dry_run": control.dry_run,
                "features": {
                    "admission": control.admission_enabled,
                    "rebalance": control.rebalance_enabled,
                    "prefetch": control.prefetch_enabled,
                }}

    # -- continuous profiling (chanamq_tpu/profile/) ------------------------

    def _profsvc(self):
        prof = getattr(self.broker, "profile", None)
        if prof is None:
            raise AdminError(
                "409 Conflict",
                "profiling disabled: boot with chana.mq.profile.enabled")
        return prof

    def _profile(self) -> dict:
        """Cost-ledger aggregate: µs/msg by stage and subsystem, loop busy
        time vs process CPU (attribution ratio), GC pauses, slow-callback
        captures."""
        return self._profsvc().snapshot()

    def _profile_stacks(self) -> str:
        """Folded stacks in flamegraph collapsed format (text/plain, one
        ``stack count`` per line) — pipe straight into flamegraph.pl."""
        prof = self._profsvc()
        if prof.sample_hz <= 0:
            raise AdminError(
                "409 Conflict",
                "stack sampler disabled: set chana.mq.profile.sample-hz")
        return prof.collapsed()

    def _profile_stage(self, name: str) -> dict:
        detail = self._profsvc().stage_detail(name)
        if detail is None:
            raise AdminError("404 Not Found", f"unknown stage {name!r}")
        return detail

    # metric name -> prometheus type; everything else in the snapshot is a
    # gauge. Latency percentiles remain exported as computed gauges for
    # dashboards that predate the proper histogram series; every Histogram
    # is ALSO exported as cumulative _bucket/_sum/_count below.
    _PROM_COUNTERS = frozenset({
        "published_msgs", "published_bytes", "delivered_msgs",
        "delivered_bytes", "returned_msgs", "confirmed_msgs",
        "expired_msgs", "dead_lettered_msgs", "connections_opened",
        "connections_closed", "connections_refused",
        "repl_events_shipped", "repl_batches_shipped",
        "repl_events_applied", "repl_resyncs", "repl_promotions",
        "repl_ack_timeouts",
        "stream_appends", "stream_append_bytes", "stream_segments_sealed",
        "stream_segments_truncated", "stream_records_delivered",
        "stream_cursor_commits", "stream_groups_created",
        "stream_group_deliveries",
        "chaos_fires", "chaos_latency", "chaos_errors", "chaos_drops",
        "chaos_disconnects", "chaos_corrupt_frames", "chaos_crashes",
        "chaos_partition_drops",
        "trace_sampled", "trace_completed", "trace_slow",
        "trace_chaos_tagged", "trace_ctx_sent", "trace_ctx_recv",
        "trace_evicted",
        "otel_forced_samples", "otel_spans_exported", "otel_batches_sent",
        "otel_export_errors", "otel_spans_shed", "otel_pull_served",
        "telemetry_ticks", "telemetry_saturated_ticks",
        "telemetry_evicted_entities", "telemetry_dropped_entities",
        "alerts_fired", "alerts_resolved",
        "shard_cross_pushes", "shard_handoffs", "shard_restarts",
        "control_ticks", "control_decisions", "control_applied",
        "control_suppressed", "control_dry_run", "control_errors",
        "lifecycle_drains_started", "lifecycle_queues_evacuated",
        "lifecycle_evacuation_retries", "lifecycle_rollbacks",
        "lifecycle_stale_epoch_refused", "lifecycle_join_rebalances",
        "lifecycle_stale_holders_cleared",
        "router_batches", "router_batch_msgs", "router_compiles",
        "router_fallback_msgs", "router_parity_mismatches",
        "profile_samples_total", "profile_slow_callbacks_total",
        "profile_gc_pauses_total", "profile_gc_pause_ns_total",
        "events_published_total", "events_dropped_total",
        "firehose_published_total", "firehose_dropped_total",
        "slo_violations_total",
        "tenancy_throttles_total", "tenancy_resumes_total",
        "tenancy_quota_refusals_total", "tenancy_acl_denials_total",
    })

    # histogram families that carry OpenMetrics exemplars under
    # ?format=openmetrics: the end-to-end latency family by name, every
    # per-stage trace family by prefix. The exempt set names histograms
    # whose observations have no trace context (replication acks land on
    # the follower, WAL commits batch many publishes, batch-size is a
    # count not a latency) — scripts/metrics_lint.py asserts every
    # exported family is in exactly one of these buckets.
    _EXEMPLAR_FAMILIES = frozenset({"publish_to_deliver_us"})
    _EXEMPLAR_PREFIXES = ("trace_",)
    _EXEMPLAR_EXEMPT = frozenset({
        "repl_ack_us", "wal_commit_us", "router_batch_size",
    })

    @staticmethod
    def _prom_label(value: str) -> str:
        return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    def _exemplars(self) -> dict:
        """family -> (trace_id, value_us, unix_ts) drawn from the trace
        rings, newest first (slow ring preferred — those are the traces
        an operator actually wants to click through to). Propagated
        traces expose their W3C id; seeded samples expose the derived
        id their exported spans carry, so the exemplar always joins."""
        from .. import trace
        from ..otel.context import derive_trace_id
        from ..trace.runtime import STAGE_KEYS

        runtime = trace.ACTIVE
        if runtime is None:
            return {}
        out: dict = {}
        ts = round(time.time(), 3)
        for pool in (runtime.slow, runtime.ring):
            for tr in reversed(pool):
                tid = (tr.w3c.trace_id if tr.w3c is not None
                       else derive_trace_id(tr.trace_id))
                if "publish_to_deliver_us" not in out:
                    out["publish_to_deliver_us"] = (tid, tr.total_us, ts)
                for i, s in enumerate(tr.slots):
                    key = STAGE_KEYS[i]
                    if s is not None and key not in out:
                        out[key] = (
                            tid, max(0.0, (s[1] - s[0]) / 1000.0), ts)
        return out

    def _prometheus(self, query: dict = None) -> str:
        """Prometheus text exposition of the broker metrics + per-queue
        gauges (exceeds the reference, which had no metrics at all —
        SURVEY.md §5 'observability': throughput was measured by grepping
        log lines). ``?format=openmetrics`` emits the same series with
        trace-id exemplars on the hot histograms and a trailing # EOF;
        the plain scrape stays byte-identical to what it always was."""
        query = query or {}
        openmetrics = query.get("format") == "openmetrics"
        exemplars = self._exemplars() if openmetrics else {}
        out: list[str] = []
        snap = self.broker.metrics_snapshot()
        # on a sharded node every worker scrapes the same metric names;
        # the shard label keeps the per-process series distinguishable
        shard_info = getattr(self.broker, "shard_info", None)
        shard_suffix = (
            f'{{shard="{self._prom_label(str(shard_info["index"]))}"}}'
            if shard_info else "")
        for key, value in snap.items():
            if isinstance(value, bool):
                value = int(value)  # e.g. memory_blocked -> 0/1 gauge
            if not isinstance(value, (int, float)):
                continue  # None percentiles before any traffic
            kind = "counter" if key in self._PROM_COUNTERS else "gauge"
            out.append(f"# TYPE chanamq_{key} {kind}")
            out.append(f"chanamq_{key}{shard_suffix} {value}")
        # proper cumulative histogram series: the stored buckets are
        # per-bound counts, so emit a running sum with +Inf last
        for name, hist in self.broker.metrics.histograms().items():
            out.append(f"# TYPE chanamq_{name} histogram")
            ex = exemplars.get(name)
            cumulative = 0
            for bound, count in zip(hist.BOUNDS, hist.buckets):
                cumulative += count
                line = f'chanamq_{name}_bucket{{le="{bound}"}} {cumulative}'
                if ex is not None and ex[1] <= bound:
                    # OpenMetrics exemplar on the first bucket that
                    # covers the sampled value, then consumed — the
                    # spec allows at most one exemplar per line
                    tid, value, ts = ex
                    line += f' # {{trace_id="{tid}"}} {value} {ts}'
                    ex = None
                out.append(line)
            line = f'chanamq_{name}_bucket{{le="+Inf"}} {hist.count}'
            if ex is not None:
                tid, value, ts = ex
                line += f' # {{trace_id="{tid}"}} {value} {ts}'
            out.append(line)
            out.append(f"chanamq_{name}_sum {hist.total_us}")
            out.append(f"chanamq_{name}_count {hist.count}")
        prof = getattr(self.broker, "profile", None)
        if prof is not None:
            # cost-ledger stage series, labeled by stage name so a single
            # PromQL expression yields µs/msg: rate(stage_ns)/rate(calls)
            from .. import profile as profile_mod

            out.append("# TYPE chanamq_profile_stage_ns_total counter")
            out.append("# TYPE chanamq_profile_stage_calls_total counter")
            for i, stage in enumerate(profile_mod.STAGES):
                labels = f'{{stage="{self._prom_label(stage)}"}}'
                out.append(
                    f"chanamq_profile_stage_ns_total{labels} "
                    f"{int(prof.stage_ns[i])}")
                out.append(
                    f"chanamq_profile_stage_calls_total{labels} "
                    f"{int(prof.stage_calls[i])}")
        registry = getattr(self.broker, "tenancy", None)
        out.append("# TYPE chanamq_queue_messages gauge")
        out.append("# TYPE chanamq_queue_ready_bytes gauge")
        out.append("# TYPE chanamq_queue_unacked gauge")
        out.append("# TYPE chanamq_queue_consumers gauge")
        for vhost in self.broker.vhosts.values():
            vl = self._prom_label(vhost.name)
            # queue series on a tenant-owned vhost carry the tenant label;
            # untenanted vhosts keep the exact two-label shape they had
            owner = (registry.tenant_of_vhost(vhost.name)
                     if registry is not None else None)
            tl = (f',tenant="{self._prom_label(owner)}"'
                  if owner is not None else "")
            for queue in vhost.queues.values():
                labels = (f'{{vhost="{vl}",'
                          f'queue="{self._prom_label(queue.name)}"{tl}}}')
                out.append(
                    f"chanamq_queue_messages{labels} {queue.message_count}")
                out.append(
                    f"chanamq_queue_ready_bytes{labels} {queue.ready_bytes}")
                out.append(
                    f"chanamq_queue_unacked{labels} {len(queue.outstanding)}")
                out.append(
                    f"chanamq_queue_consumers{labels} {queue.consumer_count}")
        streams = [
            (vhost, queue)
            for vhost in self.broker.vhosts.values()
            if not is_replica_vhost(vhost.name)
            for queue in vhost.queues.values() if queue.is_stream
        ]
        if streams:
            out.append("# TYPE chanamq_stream_retained_bytes gauge")
            out.append("# TYPE chanamq_stream_segments gauge")
            out.append("# TYPE chanamq_stream_cursor_lag gauge")
            for vhost, queue in streams:
                vl = self._prom_label(vhost.name)
                labels = f'{{vhost="{vl}",queue="{self._prom_label(queue.name)}"}}'
                out.append(
                    f"chanamq_stream_retained_bytes{labels} "
                    f"{queue.retained_bytes}")
                out.append(
                    f"chanamq_stream_segments{labels} {queue.segment_count}")
                for cursor in sorted(queue.committed):
                    clabels = (
                        f'{{vhost="{vl}",'
                        f'queue="{self._prom_label(queue.name)}",'
                        f'cursor="{self._prom_label(cursor)}"}}')
                    out.append(
                        f"chanamq_stream_cursor_lag{clabels} "
                        f"{queue.cursor_lag(cursor)}")
        federation = getattr(self.broker, "federation", None)
        if federation is not None and federation.links:
            # per-link mirror lag in records plus an up/down gauge; the
            # aggregate federation_* counters ride the plain snapshot above
            out.append("# TYPE chanamq_federation_link_lag gauge")
            out.append("# TYPE chanamq_federation_link_up gauge")
            for link in federation.links:
                labels = f'{{link="{self._prom_label(link.name)}"}}'
                out.append(
                    f"chanamq_federation_link_lag{labels} {link.total_lag()}")
                out.append(
                    f"chanamq_federation_link_up{labels} "
                    f"{int(link.state == 'up')}")
        telemetry = getattr(self.broker, "telemetry", None)
        if telemetry is not None and telemetry.engine.firing:
            # one series per firing alert instance, value 1 while firing;
            # the instance disappears from the scrape on resolve (the
            # standard ALERTS{...}-style shape, minus Prometheus itself)
            out.append("# TYPE chanamq_alert_firing gauge")
            for info in sorted(telemetry.engine.firing.values(),
                               key=lambda i: (i["rule"], i["entity"])):
                labels = (
                    f'{{rule="{self._prom_label(info["rule"])}",'
                    f'scope="{self._prom_label(info["scope"])}",'
                    f'entity="{self._prom_label(info["entity"])}",'
                    f'severity="{self._prom_label(info["severity"])}"}}')
                out.append(f"chanamq_alert_firing{labels} 1")
        if telemetry is not None and telemetry.slo is not None:
            # one budget/burn-rate pair of series per SLO spec: the
            # dashboards the burn-rate alerts point the operator at
            engine = telemetry.slo
            out.append("# TYPE chanamq_slo_budget_remaining gauge")
            out.append("# TYPE chanamq_slo_burn_rate gauge")
            for spec in engine.specs:
                status = engine.slo_status(spec)
                tl = (f',tenant="{self._prom_label(spec.tenant)}"'
                      if spec.tenant else "")
                slabels = (f'{{slo="{self._prom_label(spec.name)}",'
                           f'sli="{self._prom_label(spec.sli)}"{tl}}}')
                out.append(
                    f"chanamq_slo_budget_remaining{slabels} "
                    f"{status['budget_remaining']}")
                for pair in ("fast", "slow"):
                    blabels = (f'{{slo="{self._prom_label(spec.name)}",'
                               f'sli="{self._prom_label(spec.sli)}",'
                               f'window="{pair}"{tl}}}')
                    out.append(
                        f"chanamq_slo_burn_rate{blabels} "
                        f"{status['burn'][f'{pair}_short']['burn_rate']}")
        if registry is not None:
            # per-tenant quota/traffic series: one row per tenant, labeled
            # by tenant name (the noisy-neighbor dashboard's raw material)
            out.append("# TYPE chanamq_tenancy_tenants gauge")
            out.append(f"chanamq_tenancy_tenants {len(registry.tenants)}")
            gauges = ("connections", "channels", "queues", "bindings",
                      "resident_bytes", "tokens", "floor")
            counters = ("published", "delivered", "refused", "throttles")
            for field in gauges + ("gated",):
                out.append(f"# TYPE chanamq_tenant_{field} gauge")
            for field in counters:
                out.append(f"# TYPE chanamq_tenant_{field} counter")
            for name in sorted(registry.tenants):
                snap = registry.tenants[name].snapshot()
                labels = f'{{tenant="{self._prom_label(name)}"}}'
                for field in gauges + counters:
                    out.append(
                        f"chanamq_tenant_{field}{labels} {snap[field]}")
                out.append(
                    f"chanamq_tenant_gated{labels} {int(snap['gated'])}")
        forecaster = getattr(self.broker, "forecaster", None)
        if forecaster is not None and forecaster.forecast is not None:
            # next-tick telemetry forecast (models/service.py): one gauge
            # per feature, in the telemetry ring's units
            out.append("# TYPE chanamq_forecast gauge")
            for name, value in forecaster.forecast.items():
                out.append(
                    f'chanamq_forecast{{feature="{self._prom_label(name)}"}}'
                    f" {value}")
            if forecaster.loss is not None:
                out.append("# TYPE chanamq_forecast_loss gauge")
                out.append(f"chanamq_forecast_loss {forecaster.loss}")
        if forecaster is not None:
            accuracy = forecaster.accuracy()
            if accuracy is not None:
                # realized accuracy of past forecasts (models/service.py
                # score_tick): the series the control plane gates on
                out.append("# TYPE chanamq_forecast_error_scored counter")
                out.append(
                    f"chanamq_forecast_error_scored {accuracy['scored']}")
                out.append("# TYPE chanamq_forecast_error_mae gauge")
                for name, value in accuracy["mae"].items():
                    out.append(
                        f"chanamq_forecast_error_mae"
                        f'{{feature="{self._prom_label(name)}"}} {value}')
                last = accuracy.get("last_abs_error")
                if last:
                    out.append("# TYPE chanamq_forecast_error_last gauge")
                    for name, value in last.items():
                        out.append(
                            f"chanamq_forecast_error_last"
                            f'{{feature="{self._prom_label(name)}"}} {value}')
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"

    def _overview(self) -> dict:
        return {
            "product": "chanamq-tpu",
            "vhosts": {
                name: {
                    "active": vhost.active,
                    "exchanges": len(vhost.exchanges),
                    "queues": len(vhost.queues),
                    "messages": sum(len(q.messages) for q in vhost.queues.values()),
                    "consumers": sum(q.consumer_count for q in vhost.queues.values()),
                }
                for name, vhost in self.broker.vhosts.items()
            },
            "metrics": self.broker.metrics_snapshot(),
        }

    def _queues(self, vhost_name: str) -> list:
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return []
        return [
            {
                "name": queue.name,
                "durable": queue.durable,
                "exclusive": queue.exclusive_owner is not None,
                "auto_delete": queue.auto_delete,
                "messages": queue.message_count,
                "ready_bytes": queue.ready_bytes,
                "unacked": len(queue.outstanding),
                "consumers": queue.consumer_count,
                "ttl_ms": queue.ttl_ms,
                "arguments": queue.arguments or {},
            }
            for queue in vhost.queues.values()
        ]

    def _streams(self) -> list:
        """Every stream queue across vhosts: log shape (segments, retained
        bytes, offset range) plus per-cursor committed offset and lag.
        Replica namespaces are invisible here by construction (they never
        enter broker.vhosts) and excluded defensively anyway."""
        out = []
        for vhost in self.broker.vhosts.values():
            if is_replica_vhost(vhost.name):
                continue
            for queue in vhost.queues.values():
                if not queue.is_stream:
                    continue
                # live cursors may not have committed yet; committed
                # cursors may have detached — report the union
                names = set(queue.committed) | set(queue._cursors)
                out.append({
                    "vhost": vhost.name,
                    "name": queue.name,
                    "segments": queue.segment_count,
                    "retained_bytes": queue.retained_bytes,
                    "first_offset": queue.first_offset,
                    "next_offset": queue.next_offset,
                    "messages": queue.message_count,
                    "consumers": queue.consumer_count,
                    "max_length_bytes": queue.max_length_bytes,
                    "max_age_ms": queue.max_age_ms,
                    "cursors": {
                        name: {
                            "committed": queue.committed.get(name),
                            "attached": name in queue._cursors,
                            "lag": queue.cursor_lag(name),
                        }
                        for name in sorted(names)
                    },
                    "groups": [
                        group.snapshot()
                        for _, group in sorted(queue._groups.items())
                    ],
                })
        return out

    def _lifecycle(self):
        cluster = self.broker.cluster
        if cluster is None or cluster.membership is None:
            raise AdminError(
                "409 Conflict",
                "clustering disabled: boot with chana.mq.cluster.enabled")
        return cluster.lifecycle

    def _drain_start(self) -> dict:
        """Begin (idempotently) this node's graceful decommission: stop
        taking new holdership, evacuate every held queue, gossip `left`.
        Poll GET /admin/drain for progress."""
        return self._lifecycle().drain()

    def _drain_status(self) -> dict:
        return self._lifecycle().progress()

    def _cluster(self) -> dict:
        """Cluster membership + queue ownership as the operator sees it
        (exceeds the reference, whose admin surface was vhost-only)."""
        cluster = self.broker.cluster
        if cluster is None or cluster.membership is None:
            # membership is None until ClusterNode.start() completes: report
            # disabled rather than 500 in that window
            return {"enabled": False}
        owned = sum(
            1 for (vhost, name) in cluster.queue_metas
            if cluster.owns_queue(vhost, name))
        return {
            "enabled": True,
            "self": cluster.name,
            "members": {
                name: {"status": member.status,
                       "incarnation": member.incarnation,
                       "lifecycle": member.lifecycle}
                for name, member in cluster.membership.members.items()
            },
            "alive": cluster.membership.alive_members(),
            "placement": cluster.membership.placement_members(),
            "drain": cluster.lifecycle.progress(),
            "known_queues": len(cluster.queue_metas),
            "owned_queues": owned,
            # fencing epochs: bumped on every holdership change; stale-epoch
            # metadata and replication ships are refused
            "queue_epochs": {
                f"{vhost}/{name}": int(meta.get("epoch") or 0)
                for (vhost, name), meta in sorted(cluster.queue_metas.items())
            },
            "shard": getattr(self.broker, "shard_info", None),
            "shard_siblings": dict(cluster.uds_map),
            "replication": (
                {"enabled": False} if cluster.replication is None else {
                    "enabled": True,
                    "factor": cluster.replication.factor,
                    "sync": cluster.replication.sync,
                    "lag_events": cluster.replication.total_lag(),
                    "copies": len(cluster.replication.applier.copies),
                }),
            "interconnect": self._interconnect(cluster),
        }

    def _interconnect(self, cluster) -> dict:
        """Data-plane fast-path state: per-peer stream depth / buffered
        micro-batches (each stream reports its reconnect-backoff posture:
        current delay, consecutive failures, last error) plus the
        control-plane clients' backoff and the global binary-frame
        counters."""
        m = self.broker.metrics
        return {
            "peers": {
                # keys are (peer, transport kind); JSON wants strings
                f"{peer}#{kind}": plane.stats()
                for (peer, kind), plane in cluster._dataplanes.items()
            },
            "control": {
                name: client.backoff_state()
                for name, client in cluster.membership._clients.items()
            },
            "data_bytes_sent": m.rpc_data_bytes_sent,
            "data_bytes_recv": m.rpc_data_bytes_recv,
            "push_records": m.rpc_push_records,
            "push_batches": m.rpc_push_batches,
            "settle_records": m.rpc_settle_records,
            "settle_batches": m.rpc_settle_batches,
            "deliver_records": m.rpc_deliver_records,
            "deliver_batches": m.rpc_deliver_batches,
            "flushes": {
                "window": m.rpc_flush_window,
                "bytes": m.rpc_flush_bytes,
                "count": m.rpc_flush_count,
                "demand": m.rpc_flush_demand,
            },
        }

    def _replication(self) -> dict:
        """Per-queue replica state: role, follower ack positions, and event
        lag on owned queues; applied position on follower copies."""
        cluster = self.broker.cluster
        if cluster is None or cluster.replication is None:
            return {"enabled": False}
        return cluster.replication.status()

    def _exchanges(self, vhost_name: str) -> list:
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return []
        return [
            {
                "name": exchange.name or "(default)",
                "type": exchange.type,
                "durable": exchange.durable,
                "auto_delete": exchange.auto_delete,
                "internal": exchange.internal,
                "bindings": len(exchange.matcher.bindings()),
                "exchange_bindings": (
                    len(exchange.ex_matcher.bindings())
                    if exchange.ex_matcher is not None else 0),
            }
            for exchange in vhost.exchanges.values()
        ]
